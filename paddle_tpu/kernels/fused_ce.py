"""Fused LM-head + softmax cross-entropy (TPU memory/bandwidth kernel).

Counterpart of the reference's fused ``c_softmax_with_cross_entropy`` idea
(`paddle/fluid/operators/collective/c_softmax_with_cross_entropy_op.cc`) but
designed for XLA: the ``[N, V]`` logits tensor (e.g. 8192 x 50304, ~0.8 GB in
bf16 and double that in f32) is never materialized in HBM. The vocab dimension
is processed in chunks under ``lax.scan`` with an online logsumexp; the
backward pass recomputes each chunk's logits and feeds the two grad matmuls
directly. Costs one extra LM-head matmul (~10% of model FLOPs) and saves
~2.5 GB of HBM traffic + residency per step on GPT-2-small at 8x1024 —
which is what lets the whole model train without full-block remat.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _pick_chunks(v: int) -> int:
    """Chunk count <= 4 that divides the (padded) vocab. Chunks are UNROLLED
    (python loop) so the per-chunk matmuls stay independent in the graph —
    lax.scan would serialize them behind the cheap online-logsumexp carry."""
    for nc in (4, 3, 2):
        if v % nc == 0 and v // nc >= 4096:
            return nc
    return 1


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def fused_linear_cross_entropy(h, w, labels):
    loss, _ = _flce_fwd(h, w, labels)
    return loss


def _chunk_logits(h, w_c):
    """[N,H] x [vc,H] -> [N,vc] in bf16 with f32 accumulation (MXU-friendly)."""
    return jax.lax.dot_general(
        h.astype(jnp.bfloat16), w_c.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)


def _flce_fwd(h, w, labels):
    n, hid = h.shape
    v = w.shape[0]
    nc = _pick_chunks(v)
    vc = v // nc
    labels = labels.astype(jnp.int32)

    # independent per-chunk (max, sumexp-at-own-max, picked-logit) ...
    ms, ls, picks = [], [], []
    for c in range(nc):
        logits = _chunk_logits(h, w[c * vc:(c + 1) * vc])   # [N, vc] f32
        m_c = jnp.max(logits, axis=-1)
        l_c = jnp.sum(jnp.exp(logits - m_c[:, None]), axis=-1)
        idx = labels - c * vc
        in_chunk = (idx >= 0) & (idx < vc)
        safe = jnp.where(in_chunk, idx, 0)
        got = jnp.take_along_axis(logits, safe[:, None], axis=1)[:, 0]
        ms.append(m_c)
        ls.append(l_c)
        picks.append(jnp.where(in_chunk, got, -jnp.inf))
    # ... then a cheap tree-merge into the global logsumexp
    m = ms[0]
    for m_c in ms[1:]:
        m = jnp.maximum(m, m_c)
    l = ls[0] * jnp.exp(ms[0] - m)
    for m_c, l_c in zip(ms[1:], ls[1:]):
        l = l + l_c * jnp.exp(m_c - m)
    picked = picks[0]
    for pk in picks[1:]:
        picked = jnp.maximum(picked, pk)
    lse = m + jnp.log(l)
    # out-of-range labels (e.g. the conventional -100 padding / ignore_index)
    # contribute zero loss and zero gradient, matching F.cross_entropy
    valid = (labels >= 0) & (labels < v)
    loss = jnp.where(valid, lse - picked, 0.0)
    return loss, (h, w, labels, lse)


def _flce_bwd(res, dloss):
    h, w, labels, lse = res
    n, hid = h.shape
    v = w.shape[0]
    nc = _pick_chunks(v)
    vc = v // nc
    valid = (labels >= 0) & (labels < v)
    dl = dloss.astype(jnp.float32) * valid.astype(jnp.float32)

    dh = jnp.zeros((n, hid), jnp.float32)
    dws = []
    for c in range(nc):
        w_c = w[c * vc:(c + 1) * vc]
        logits = _chunk_logits(h, w_c)                      # recompute [N, vc]
        p = jnp.exp(logits - lse[:, None])                  # softmax chunk
        idx = labels - c * vc
        in_chunk = (idx >= 0) & (idx < vc)
        onehot = (jnp.arange(vc, dtype=jnp.int32)[None, :] ==
                  idx[:, None]) & in_chunk[:, None]
        dlogits = ((p - onehot.astype(jnp.float32)) *
                   dl[:, None]).astype(jnp.bfloat16)        # [N, vc]
        dh = dh + jax.lax.dot_general(
            dlogits, w_c.astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dws.append(jax.lax.dot_general(
            dlogits, h.astype(jnp.bfloat16),
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32))
    dw = jnp.concatenate(dws, axis=0).astype(w.dtype)
    return dh.astype(h.dtype), dw, None


fused_linear_cross_entropy.defvjp(_flce_fwd, _flce_bwd)
