"""ONE kernel registry: named op -> candidate impls -> viability predicate
-> measured winner (ROADMAP item 4).

Before this module, every switchable kernel carried its own dispatch glue:
flash attention had `autotune.flash_winner` + a flag switch, paged decode
attention had `autotune.paged_winner` + its own flag + its own counter,
ring/Ulysses had a dict lookup in `nn/functional/attention.py`, and the
fused CE / fused layernorm sites hand-rolled their gating inline. Each new
kernel (the ragged prefill kernel, the fused sampler) would have added a
fifth and sixth copy. This module is the single replacement:

- **Ops** are registered by NAME with (a) the full impl universe and (b) a
  viability predicate (`candidates(ctx)`) that returns the impls actually
  runnable on this backend for this call — backend viability decided by
  NAME/probe, never by executing an op (`kernels/pallas/_compat.py`).
- **Dispatch** (`dispatch()`) resolves one call site's impl: a forced flag
  value wins (validated against the op's universe), a single viable
  candidate pins itself, and multiple candidates defer to the op's
  measured-winner hook (the synthetic-workload measurement lives with the
  op's adapter in `kernels/autotune.py`, which calls back into
  :func:`select` below). Every resolution counts
  ``kernel.dispatch.{op}.{impl}`` — a TRACE-TIME counter (once per program
  build per call site), plus any legacy alias counter the op declares
  (``paged_attention.impl.{impl}`` predates the registry and stays pinned
  by tests).
- **The winner table** (`select()`) is the PR 7 measured-selection policy
  generalized: in-memory cache -> single-candidate short circuit ->
  persisted winner -> measure every viable candidate and keep the best.
  Keys are ``(op-tag, backend, shape-class..., dtype[, variant])`` tuples.
- **Persistence** folds the PR 7 on-disk table in
  (``PADDLE_AUTOTUNE_CACHE``): same version-1 ``{"winners": {repr(key):
  impl}}`` schema, so every legacy file written by `flash_winner` /
  `paged_winner` loads as-is — and a PRE-version bare ``{key: winner}``
  mapping (the oldest format) is migrated on first load. Corrupt or stale
  files are ignored, never fatal; a persisted winner outside the current
  viable set is discarded (a table copied from a TPU host cannot poison a
  CPU one).

`kernels/autotune.py` keeps the measurement probes (`_measure`,
`_backend_kind`, the candidate lists) and the back-compat wrappers
(`flash_winner`/`paged_winner`) — those are the op ADAPTERS; the registry
is the one dispatch + persistence + observability layer under them.
"""
from __future__ import annotations

import ast
import json
import logging
import os
from dataclasses import dataclass, field
from typing import Callable

from paddle_tpu.observability import metrics

_LOG = logging.getLogger("paddle_tpu.kernels.registry")

__all__ = ["KernelOp", "register_op", "ops", "dispatch", "count", "select",
           "table", "clear"]


@dataclass
class KernelOp:
    """One named kernel op.

    ``impls`` is the full universe of impl names a forced flag may name;
    ``candidates(ctx)`` returns the subset VIABLE for this call (backend,
    shape, dtype parity — the ctx keys are op-specific), preference-ordered
    (index 0 is the no-measurement default). ``alias_counter`` keeps a
    pre-registry counter prefix alive alongside ``kernel.dispatch.*``."""
    name: str
    impls: tuple
    candidates: Callable[[dict], list] = field(repr=False, default=None)
    flag: str | None = None
    alias_counter: str | None = None


_OPS: dict[str, KernelOp] = {}

# measured winners {key: (winner, {impl: seconds})} — `kernels/autotune.py`
# aliases this object as its `_CACHE` (tests introspect it there), so it is
# mutated IN PLACE only, never rebound.
_TABLE: dict = {}

_DISK_VERSION = 1
_DISK_STATE: dict = {"path": None, "table": None}   # loaded-once per path


def register_op(name, impls, candidates=None, flag=None, alias_counter=None):
    """Register (or re-register) one op. Idempotent by name so re-imports
    in tests never duplicate."""
    if candidates is None:
        all_impls = tuple(impls)
        candidates = lambda ctx: list(all_impls)  # noqa: E731
    _OPS[name] = KernelOp(name=name, impls=tuple(impls),
                          candidates=candidates, flag=flag,
                          alias_counter=alias_counter)
    return _OPS[name]


def ops() -> dict:
    return dict(_OPS)


def table() -> dict:
    """{signature: (winner, {impl: seconds})} — measured decisions."""
    return dict(_TABLE)


def clear():
    _TABLE.clear()
    _DISK_STATE["path"] = _DISK_STATE["table"] = None


def count(op: str, impl: str):
    """The per-site trace-time dispatch counter: every resolution lands in
    ``kernel.dispatch.{op}.{impl}`` (and the op's legacy alias, if any).
    Selections run at trace time, so these count program BUILDS per call
    site, not executions."""
    metrics.counter(f"kernel.dispatch.{op}.{impl}").inc()
    o = _OPS.get(op)
    if o is not None and o.alias_counter:
        metrics.counter(f"{o.alias_counter}.{impl}").inc()


def dispatch(op: str, *, forced=None, ctx=None, winner=None,
             require_viable=False) -> str:
    """Resolve ONE call site's impl and count it.

    forced   : a flag value ("auto"/None defer to selection). Must name an
               impl in the op's universe — an unknown name is a loud
               config error, not a silent xla fallback. Forcing an impl
               outside the VIABLE set is allowed by default (interpret-
               mode parity testing forces pallas off-TPU on purpose)
               unless ``require_viable`` degrades it to the first viable
               candidate (the fused-CE "fused wanted but mp>1" rule).
    ctx      : op-specific viability context for ``candidates(ctx)``.
    winner   : zero-arg measured-selection hook (the op adapter in
               kernels/autotune.py, which calls :func:`select`); consulted
               only when >1 candidate is viable. Without one the first
               viable candidate wins.
    """
    o = _OPS.get(op)
    if o is None:
        raise KeyError(f"unknown kernel op {op!r}; registered: "
                       f"{sorted(_OPS)}")
    cands = o.candidates(ctx or {})
    if forced not in (None, "auto"):
        if forced not in o.impls:
            raise ValueError(
                f"kernel op {op!r} has no impl {forced!r}; known impls: "
                f"{list(o.impls)}")
        impl = forced if (forced in cands or not require_viable) \
            else cands[0]
    elif winner is not None:
        # the adapter owns the winner-table entry even for a single
        # candidate (a pinned impl is still a recorded decision)
        impl = winner()
        if impl not in cands:
            # defense in depth: an adapter whose candidate list drifted
            # from the dispatch-level viability ctx must not smuggle a
            # non-viable impl past the gate — degrade to the first
            # viable candidate and say so
            _LOG.warning(
                "registry: %s winner %r outside the viable set %s — "
                "using %r", op, impl, cands, cands[0])
            impl = cands[0]
    else:
        impl = cands[0]
    count(op, impl)
    return impl


# ----------------------------------------------------------- winner table


def select(op: str, key: tuple, candidates: list, measure,
           verbose_tag: str | None = None) -> str:
    """Measured-winner resolution for one (op, signature): in-memory table
    -> single-candidate pin -> persisted winner -> measure every candidate
    (``measure(impl) -> seconds``; a candidate that raises is data, not an
    error) and keep the best. The winner is cached in memory and, when
    ``PADDLE_AUTOTUNE_CACHE`` names a table, persisted on disk."""
    hit = _TABLE.get(key)
    if hit is not None:
        return hit[0]
    if len(candidates) == 1:
        _TABLE[key] = (candidates[0], {})
        return candidates[0]
    disk = _disk_lookup(key, candidates)
    if disk is not None:
        _TABLE[key] = (disk, {})
        return disk
    timings = {}
    for impl in candidates:
        try:
            timings[impl] = measure(impl)
        except Exception as e:  # noqa: BLE001 — a failing candidate is
            _LOG.info("registry: %s/%s failed to measure: %s",
                      op, impl, e)  # data, not an error (ref behavior)
            continue
    winner = min(timings, key=timings.get) if timings else candidates[0]
    try:
        from paddle_tpu.framework.flags import flag_value
        verbose = flag_value("autotune_verbose")
    except Exception:  # noqa: BLE001 — flags registry unavailable
        verbose = False
    if verbose:
        _LOG.warning("autotune %s %s -> %s (%s)", verbose_tag or op, key,
                     winner,
                     {k: f"{v * 1e3:.2f}ms" for k, v in timings.items()})
    _TABLE[key] = (winner, timings)
    _disk_store(key, winner)
    return winner


# ------------------------------------------------------------ persistence


def _disk_path():
    return os.environ.get("PADDLE_AUTOTUNE_CACHE") or None


def _parse_disk(data, count_migrated=True) -> dict:
    """Accept every table generation ever written:

    - version-1 ``{"version": 1, "winners": {repr(key): impl}}`` (the PR 7
      format `flash_winner`/`paged_winner` wrote — loads as-is, the
      registry keys those two ops identically);
    - the PRE-version bare ``{repr(key): impl}`` mapping — migrated in
      (counted on ``autotune.disk_migrated``) so a fleet's oldest cache
      files keep their winners;
    - anything else (future version stamp, wrong shapes) -> empty table.
    """
    if not isinstance(data, dict):
        return {}
    if "version" in data or "winners" in data:
        if data.get("version") != _DISK_VERSION:
            return {}
        winners = data.get("winners")
        return winners if isinstance(winners, dict) else {}
    # legacy pre-version file: a bare {key: winner} mapping. Only migrate
    # entries that look like our repr'd tuple keys with string winners.
    migrated = {k: v for k, v in data.items()
                if isinstance(k, str) and k.startswith("(")
                and isinstance(v, str)}
    if migrated and count_migrated:
        metrics.counter("autotune.disk_migrated").inc(len(migrated))
    return migrated


def _load_disk_table(path, count_migrated=True) -> dict:
    """Read the persisted winner table; ANY failure (missing, corrupt,
    wrong schema) degrades to an empty table — never fatal.
    ``count_migrated=False`` is the store-path re-read: only the
    lookup-time load counts legacy entries, so `autotune.disk_migrated`
    reports each migrated entry ONCE."""
    try:
        with open(path) as f:
            data = json.load(f)
        return _parse_disk(data, count_migrated=count_migrated)
    except Exception as e:  # noqa: BLE001 — a bad cache file is advisory
        if not isinstance(e, FileNotFoundError):
            _LOG.info("registry: ignoring unreadable cache %s: %s", path, e)
        return {}


def _disk_lookup(key, viable):
    """Persisted winner for ``key``, or None. Winners outside the backend's
    ``viable`` candidate list are stale (table copied across backends or an
    impl renamed) and are ignored."""
    path = _disk_path()
    if path is None:
        return None
    if _DISK_STATE["path"] != path or _DISK_STATE["table"] is None:
        _DISK_STATE["path"] = path
        _DISK_STATE["table"] = _load_disk_table(path)
    win = _DISK_STATE["table"].get(repr(key))
    if isinstance(win, str) and win in viable:
        metrics.counter("autotune.disk_hits").inc()
        return win
    return None


def _disk_store(key, winner):
    """Merge one measured winner into the on-disk table (atomic replace;
    re-reads first so concurrent processes lose at most their own entry).
    Failures are logged and swallowed — persistence is an optimization."""
    path = _disk_path()
    if path is None:
        return
    try:
        tab = _load_disk_table(path, count_migrated=False)
        tab[repr(key)] = winner
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"version": _DISK_VERSION, "winners": tab}, f,
                      sort_keys=True)
        os.replace(tmp, path)
        _DISK_STATE["path"], _DISK_STATE["table"] = path, tab
    except Exception as e:  # noqa: BLE001
        _LOG.info("registry: cache write to %s failed: %s", path, e)


def parse_key(repr_key: str):
    """Best-effort parse of a persisted key back into its tuple (registry
    introspection / tests); None when unparseable."""
    try:
        return ast.literal_eval(repr_key)
    except Exception:  # noqa: BLE001
        return None


# ------------------------------------------------------- built-in op set
#
# Candidate providers import lazily: viability consults the autotune
# backend probe (`_backend_kind`) and the Mosaic lowering probe
# (`pallas/_compat.py`) at CALL time, so monkeypatched probes (tests) and
# a tunnel that learns to lower Mosaic mid-fleet both take effect without
# re-registration.


def _flash_cands(ctx):
    from paddle_tpu.kernels import autotune
    return autotune._flash_candidates(
        ctx.get("backend", autotune._backend_kind()),
        ctx.get("tileable", False),
        ctx.get("shape_q", (1, 1, 1, 1)), ctx.get("shape_k", (1, 1, 1, 1)))


def _paged_cands(ctx):
    from paddle_tpu.kernels import autotune
    return autotune._paged_candidates(
        ctx.get("backend", autotune._backend_kind()))


def _prefill_cands(ctx):
    from paddle_tpu.kernels import autotune
    cands = autotune._paged_candidates(
        ctx.get("backend", autotune._backend_kind()))
    if not ctx.get("parity", True):
        # the pallas arm reads the PAGE POOL; when the pool dtype narrows
        # the compute dtype (bf16 pages under f32 weights, non-quant), the
        # one-shot XLA arm attends the raw full-precision K/V — offering
        # pallas there would silently change numerics, so it is not viable
        cands = [c for c in cands if c != "pallas"]
    return cands


def _sp_cands(ctx):
    cands = ["ring"]
    if ctx.get("heads", 1) % max(ctx.get("sp", 1), 1) == 0:
        cands.append("ulysses")
    return cands


def _fused_ce_cands(ctx):
    # the fused chunked-vocab CE assumes the full [V, H] head on every
    # rank; under mp the vocab is sharded and only the dense parallel CE
    # is correct
    return ["fused", "dense"] if ctx.get("mp", 1) == 1 else ["dense"]


register_op("flash_attention",
            impls=("xla", "dense", "splash", "mosaic", "authored"),
            candidates=_flash_cands, flag="tpu_flash_impl")
register_op("paged_attention", impls=("xla", "pallas"),
            candidates=_paged_cands, flag="tpu_paged_impl",
            alias_counter="paged_attention.impl")
register_op("prefill_attention", impls=("xla", "pallas"),
            candidates=_prefill_cands, flag="tpu_prefill_impl")
register_op("fused_sampling", impls=("xla",))
register_op("sp_attention", impls=("ring", "ulysses"),
            candidates=_sp_cands)
register_op("fused_ce", impls=("fused", "dense"),
            candidates=_fused_ce_cands)
register_op("fused_layernorm", impls=("pallas",))
register_op("fused_rope", impls=("pallas",))
