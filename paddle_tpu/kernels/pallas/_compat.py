"""Backend probe shared by the authored Pallas kernels."""
from __future__ import annotations


def default_interpret() -> bool:
    """True when pallas_call must run in interpreter mode.

    Any non-TPU backend interprets; so does the experimental 'axon' dev
    tunnel, which reports platform "tpu" but cannot lower Mosaic (trace-time
    RecursionError). Probe by backend NAME only — executing an op to find out
    poisons a tunnel's stream (same rule as fft._fft_on_device).
    """
    import jax

    if jax.default_backend() != "tpu":
        return True
    try:
        from jax._src import xla_bridge
        return "axon" in xla_bridge.backends()
    except Exception:
        return False
