"""Backend probe shared by the authored Pallas kernels and the kernel
registry's viability predicates.

Historically this was a hardcoded platform check: any tpu-named backend
served by the experimental 'axon' dev tunnel was pinned to interpret mode
forever, because the tunnel could not lower Mosaic (trace-time
RecursionError) and executing an unsupported op there poisons the device
stream. That pin had a cost (VERDICT round-5 item 6): the day the tunnel
gained Mosaic support, nothing would have noticed.

The rule is now RE-PROBED once per process, BY NAME and by LOWERING only:

- non-TPU platforms never probe (interpret mode, as before);
- a tpu-named backend lowers one trivial Mosaic kernel —
  ``jax.jit(...).lower(...)`` traces and lowers but never executes, so a
  tunnel that cannot lower fails the probe harmlessly at trace time while
  one that CAN enables the compiled Pallas arms (and their registry
  candidates, `kernels/autotune.py::_paged_candidates`) the day it learns
  to, with zero code changes;
- the result is cached per backend NAME for the life of the process
  (``_PROBED``), so the probe costs one lowering per process, not one per
  trace.
"""
from __future__ import annotations

_PROBED: dict[str, bool] = {}   # backend name -> Mosaic lowering works


def _tunnel_name() -> str:
    """'axon' when the experimental tunnel backs the tpu platform, else
    'tpu' (probe key only — never used to gate without probing)."""
    try:
        from jax._src import xla_bridge
        if "axon" in xla_bridge.backends():
            return "axon"
    except Exception:  # noqa: BLE001
        pass
    return "tpu"


def probe_mosaic_lowering(name: str) -> bool:
    """LOWER (never execute, never compile-to-binary) one trivial Mosaic
    kernel, once per process per backend name. A backend that cannot
    lower Mosaic raises at trace/lower time without touching the device
    stream — exactly the safe half of the historical failure mode."""
    if name in _PROBED:
        return _PROBED[name]
    try:
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def _copy(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def _fn(x):
            return pl.pallas_call(
                _copy,
                out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32))(x)

        jax.jit(_fn).lower(jax.ShapeDtypeStruct((8, 128), jnp.float32))
        ok = True
    except Exception:  # noqa: BLE001 — any lowering failure means interpret
        ok = False
    _PROBED[name] = ok
    return ok


def mosaic_supported() -> bool:
    """True when the current backend can run compiled Mosaic kernels.
    False on every non-TPU platform; on a tpu-named backend the answer is
    the per-process lowering probe keyed by backend name."""
    try:
        import jax
        if jax.default_backend() != "tpu":
            return False
    except Exception:  # noqa: BLE001 — a dead backend interprets
        return False
    return probe_mosaic_lowering(_tunnel_name())


def default_interpret() -> bool:
    """True when pallas_call must run in interpreter mode (the inverse of
    :func:`mosaic_supported` — kept as the name every kernel imports)."""
    return not mosaic_supported()
