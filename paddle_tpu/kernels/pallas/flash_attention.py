"""Authored Pallas TPU flash-attention forward kernel.

Online-softmax blockwise attention (Dao et al.) written directly against the
Pallas TPU API — the in-repo counterpart of the reference's fused attention
CUDA op (`paddle/fluid/operators/fused/fused_attention_op.cu`, which is
non-flash: it materialises the full [S, S] score matrix via `fmha_ref.h`).
Here scores never leave VMEM: the kernel streams K/V blocks through the MXU
and keeps a running (max, denom, accumulator) triple per query block, so HBM
traffic is O(S·D) instead of O(S²).

Backward is ALSO authored (round-2 verdict asked for it): two Pallas
kernels recompute the probabilities blockwise from the forward's saved
logsumexp — one gridded over query blocks producing dQ, one over key blocks
producing dK/dV — so the backward, like the forward, never materializes an
[S, S] tensor in HBM (Dao et al. algorithm 2).

Layout: [B, H, S, D] (callers with paddle's [B, S, H, D] transpose first —
see `paddle_tpu/kernels/flash_attention.py`).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                block_q, block_k, seq_q, seq_k):
    # q_ref: [1, block_q, D]; k_ref/v_ref: [1, seq_k, D]; o_ref: [1, block_q, D]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale

    num_kb = pl.cdiv(seq_k, block_k)
    if causal:
        # bottom-right-aligned diagonal (matches _reference's tril k=sk-sq):
        # row qpos may attend kpos <= qpos + (seq_k - seq_q). Blocks fully
        # above that line contribute nothing.
        off = seq_k - seq_q
        last = ((qi + 1) * block_q - 1 + off) // block_k + 1
        num_kb = jnp.minimum(num_kb, last)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        kpos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = kpos < seq_k            # ragged tail: block padding is garbage
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask &= kpos <= qpos + (seq_k - seq_q)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(p, v,
                                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    a0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, a0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(jnp.maximum(l, 1e-30)))[:, 0]


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    # dynamic slices clamp at the array edge, so a ragged K tail must be
    # zero-padded up front (the kpos mask discards the padding)
    pad_k = (-sk) % block_k
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0)))
    sk_pad = sk + pad_k
    grid = (bh, pl.cdiv(sq, block_q))
    kern = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                             block_q=block_q, block_k=block_k, seq_q=sq,
                             seq_k=sk)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk_pad, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk_pad, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(q.shape[:2], jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _reference(q, k, v, sm_scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               sm_scale, causal, block_q, block_k, seq_q, seq_k):
    # q/do/dq: [1, block_q, D]; k/v: [1, sk_pad, D]; lse/delta: [1, block_q]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]
    delta = delta_ref[0]

    num_kb = pl.cdiv(seq_k, block_k)
    if causal:
        off = seq_k - seq_q
        last = ((qi + 1) * block_q - 1 + off) // block_k + 1
        num_kb = jnp.minimum(num_kb, last)

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q * sm_scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        kpos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = kpos < seq_k
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask &= kpos <= qpos + (seq_k - seq_q)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq + jnp.dot(ds, k,
                            preferred_element_type=jnp.float32) * sm_scale

    dq0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    dq = jax.lax.fori_loop(0, num_kb, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, *, sm_scale, causal, block_q, block_k, seq_q, seq_k):
    # k/v/dk/dv: [1, block_k, D]; q/do: [1, sq_pad, D]; lse/delta: [1, sq_pad]
    kj = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)

    num_qb = pl.cdiv(seq_q, block_q)
    first = jnp.int32(0)
    if causal:
        # query rows strictly above kpos_min - (sk - sq) see nothing here
        off = seq_k - seq_q
        first = jnp.maximum(jnp.int32(0), (kj * block_k - off) // block_q)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q)]
        delta = delta_ref[0, pl.ds(i * block_q, block_q)]
        s = jax.lax.dot_general(q * sm_scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        qpos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        kpos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = (kpos < seq_k) & (qpos < seq_q)   # ragged q AND k tails
        if causal:
            mask &= kpos <= qpos + (seq_k - seq_q)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dv_new = dv + jnp.dot(p.T, do,
                              preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dk_new = dk + jnp.dot(ds.T, q,
                              preferred_element_type=jnp.float32) * sm_scale
        return dk_new, dv_new

    d = k_ref.shape[-1]
    z = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first, num_qb, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(q, k, v, out, lse, do, sm_scale, causal, block_q, block_k,
         interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    pad_k = (-sk) % block_k
    pad_q = (-sq) % block_q
    kp = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0))) if pad_k else k
    vp = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0))) if pad_k else v
    qp = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0))) if pad_q else q
    dop = jnp.pad(do, ((0, 0), (0, pad_q), (0, 0))) if pad_q else do
    lsep = jnp.pad(lse, ((0, 0), (0, pad_q))) if pad_q else lse
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                                   # [bh, sq]
    deltap = jnp.pad(delta, ((0, 0), (0, pad_q))) if pad_q else delta
    sk_pad, sq_pad = sk + pad_k, sq + pad_q

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_q=sq,
                          seq_k=sk),
        grid=(bh, pl.cdiv(sq, block_q)),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk_pad, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk_pad, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, kp, vp, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_q=sq,
                          seq_k=sk),
        grid=(bh, pl.cdiv(sk, block_k)),
        in_specs=[
            pl.BlockSpec((1, sq_pad, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, sq_pad, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, sq_pad), lambda b, j: (b, 0)),
            pl.BlockSpec((1, sq_pad), lambda b, j: (b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(qp, k, v, dop, lsep, deltap)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _bwd(q, k, v, out, lse, g, sm_scale, causal, block_q, block_k,
                interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal=False, sm_scale=None, block_q=128,
                    block_k=128, interpret=None):
    """Blockwise flash attention. q/k/v: [B, H, S, D] jax arrays.

    ``interpret=None`` auto-selects the Pallas interpreter off-TPU so tests run
    on the CPU mesh; on TPU the kernel compiles through Mosaic.
    """
    if interpret is None:
        from paddle_tpu.kernels.pallas._compat import default_interpret
        interpret = default_interpret()
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if causal and sq > sk:
        # bottom-right alignment leaves rows with no visible keys; the
        # reference math degenerates to a uniform softmax over -1e30 scores
        # there, which a streaming kernel cannot reproduce blockwise
        raise NotImplementedError(
            "causal flash_attention requires seq_q <= seq_k "
            f"(got {sq} > {sk})")
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    out = _flash(qf, kf, vf, float(sm_scale), bool(causal), int(block_q),
                 int(block_k), bool(interpret))
    return out.reshape(b, h, sq, d)
