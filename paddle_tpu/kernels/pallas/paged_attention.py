"""Authored Pallas TPU ragged paged-attention decode kernel (arxiv 2604.15464).

The XLA reference path (`kernels/paged_attention.py`) materializes the FULL
padded ``[B, pages_per_slot * page_size, nh, dh]`` K and V windows per layer
per step — HBM traffic and FLOPs scale with the pool's *capacity*, not the
live sequences' lengths. This kernel is the drop-in the reference module was
shaped for:

- **grid over (sequence, head)** — one grid cell owns one (b, h) pair and
  produces its ``[dh]`` context vector;
- **pages streamed block-by-block** — the K/V pools stay in HBM
  (``memory_space=ANY``); each cell DMAs one ``[page_size, dh]`` page slice
  at a time into a double-buffered VMEM scratch (next page's DMA in flight
  while the current page is on the MXU) and folds it into a running online
  softmax (max, denom, accumulator);
- **length-aware stop** — the page loop's trip count is
  ``ceil((pos[b]+1) / page_size)``, read from the scalar-prefetched ``pos``,
  so compute AND DMA traffic scale with each sequence's true length instead
  of ``pages_per_slot``. A 1-token sequence in a 4096-token slot touches one
  page, not 256.

Numerics match the reference: f32 scores, f32 online softmax, masked tail
positions excluded — parity with the XLA path is enforced by
tests/test_paged_pallas.py in interpret mode on CPU; on TPU the kernel
compiles through Mosaic. Selection between the two lives in
`kernels/paged_attention.py` (``FLAGS_tpu_paged_impl``), backend viability
decided by NAME in `kernels/pallas/_compat.py`, measured winners in
`kernels/autotune.py`.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def pages_needed(pos, page_size):
    """Trip count of the kernel's page loop for position ``pos`` — the
    length-aware stop: ``ceil((pos + 1) / page_size)``, NOT pages_per_slot."""
    return (pos + page_size) // page_size


def _decode_kernel(pos_ref, pt_ref, q_ref, k_hbm, v_hbm, o_ref, *rest,
                   page_size, scale, quant=False, has_visits=False):
    # one grid cell per (sequence b, head h): q_ref [1, 1, dh] in VMEM,
    # k_hbm/v_hbm the full [num_pages, page_size, nh, dh] pools in HBM,
    # pos/page_table scalar-prefetched into SMEM. The visits output exists
    # only under return_visits (parity tests) — the serving kernel is
    # single-output. Under ``quant`` the pools are int8 with f32 scale
    # pools [num_pages, page_size, nh] riding two extra HBM operands; each
    # page's [page_size] scale slice DMAs in the same double-buffered
    # rhythm as its values and the dequant happens in-register, right
    # after the copy lands — so DMA traffic is the int8 bytes, never a
    # widened page.
    if quant:
        ks_hbm, vs_hbm, o_ref, *rest = o_ref, rest[0], rest[1], *rest[2:]
    else:
        ks_hbm = vs_hbm = None
    if has_visits:                     # static flag, like `quant` — never
        visits_ref, rest = rest[0], rest[1:]   # inferred from arg counts
    else:
        visits_ref = None
    if quant:
        kbuf, vbuf, ksbuf, vsbuf, sem = rest
    else:
        kbuf, vbuf, sem = rest
        ksbuf = vsbuf = None
    b = pl.program_id(0)
    h = pl.program_id(1)
    pos = pos_ref[b]
    npages = pages_needed(pos, page_size)
    if visits_ref is not None:
        visits_ref[0, 0] = npages      # the loop bound, exported for tests

    def dma(slot, j):
        # page j of sequence b: DMA this head's [page_size, dh] slice of the
        # page from HBM into the double buffer (plus its [page_size] scale
        # slice when the pool is int8)
        pg = pt_ref[b, j]
        copies = [pltpu.make_async_copy(k_hbm.at[pg, :, h, :], kbuf.at[slot],
                                        sem.at[0, slot]),
                  pltpu.make_async_copy(v_hbm.at[pg, :, h, :], vbuf.at[slot],
                                        sem.at[1, slot])]
        if quant:
            copies += [pltpu.make_async_copy(ks_hbm.at[pg, :, h],
                                             ksbuf.at[slot],
                                             sem.at[2, slot]),
                       pltpu.make_async_copy(vs_hbm.at[pg, :, h],
                                             vsbuf.at[slot],
                                             sem.at[3, slot])]
        return copies

    for c in dma(0, 0):
        c.start()
    q = q_ref[0, 0][None].astype(jnp.float32) * scale          # [1, dh]

    def body(j, carry):
        m, l, acc = carry
        slot = jax.lax.rem(j, jnp.int32(2))
        nslot = jax.lax.rem(j + jnp.int32(1), jnp.int32(2))

        @pl.when(j + jnp.int32(1) < npages)
        def _():                       # overlap: next page's DMA in flight
            for c in dma(nslot, j + jnp.int32(1)):
                c.start()

        for c in dma(slot, j):
            c.wait()
        k = kbuf[slot].astype(jnp.float32)                     # [ps, dh]
        v = vbuf[slot].astype(jnp.float32)
        if quant:
            # dequantize in-register AFTER the page copy: the DMA moved
            # int8 bytes; only the VMEM-resident working tile widens
            k = k * ksbuf[slot][:, None]
            v = v * vsbuf[slot][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [1, ps]
        kpos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        s = jnp.where(kpos <= pos, s, NEG_INF)  # tail of the last page
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    dh = q_ref.shape[-1]
    m0 = jnp.full((1, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((1, 1), jnp.float32)
    a0 = jnp.zeros((1, dh), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, npages, body, (m0, l0, a0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30))[0].astype(o_ref.dtype)


def paged_attention(q, k_pages, v_pages, page_table, pos, *, interpret=None,
                    return_visits=False, k_scale=None, v_scale=None):
    """One decode step of ragged paged attention. Same contract as the XLA
    reference `kernels.paged_attention.paged_attention`:

    q          : [B, nh, dh] current-token query
    k_pages    : [num_pages, page_size, nh, dh] (one layer)
    v_pages    : [num_pages, page_size, nh, dh]
    page_table : [B, pages_per_slot] int32
    pos        : [B] int32 — attends positions 0..pos inclusive
    k_scale/v_scale : optional [num_pages, page_size, nh] f32 — int8 pools:
                 each visited page's scale slice DMAs alongside its values
                 and the dequant runs in-register after the copy, so the
                 kernel's HBM traffic is the int8 bytes (~1/4 of f32)
    returns    : [B, nh, dh] in q.dtype; with ``return_visits=True`` also
                 the per-(b, h) page-loop trip counts [B, nh] int32 — the
                 ragged-stop proof the parity tests assert on.

    ``interpret=None`` auto-selects the Pallas interpreter off-TPU (CPU
    parity tests); on TPU the kernel compiles through Mosaic.
    """
    if interpret is None:
        from paddle_tpu.kernels.pallas._compat import default_interpret
        interpret = default_interpret()
    quant = k_scale is not None
    b, nh, dh = q.shape
    ps = k_pages.shape[1]
    scale = 1.0 / (dh ** 0.5)
    kern = functools.partial(_decode_kernel, page_size=ps,
                             scale=float(scale), quant=quant,
                             has_visits=bool(return_visits))
    out_specs = [pl.BlockSpec((1, 1, dh), lambda i, j, *_: (i, j, 0))]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    if return_visits:
        out_specs.append(pl.BlockSpec((1, 1), lambda i, j, *_: (i, j)))
        out_shape.append(jax.ShapeDtypeStruct((b, nh), jnp.int32))
    in_specs = [
        pl.BlockSpec((1, 1, dh), lambda i, j, *_: (i, j, 0)),
        pl.BlockSpec(memory_space=pltpu.ANY),         # K pool stays in HBM
        pl.BlockSpec(memory_space=pltpu.ANY),         # V pool stays in HBM
    ]
    scratch = [
        pltpu.VMEM((2, ps, dh), k_pages.dtype),       # K double buffer
        pltpu.VMEM((2, ps, dh), v_pages.dtype),       # V double buffer
    ]
    operands = [q, k_pages, v_pages]
    if quant:
        in_specs += [pl.BlockSpec(memory_space=pltpu.ANY),   # K scales (HBM)
                     pl.BlockSpec(memory_space=pltpu.ANY)]   # V scales (HBM)
        scratch += [pltpu.VMEM((2, ps), jnp.float32),        # scale buffers
                    pltpu.VMEM((2, ps), jnp.float32)]
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    # semaphore rows: one per in-flight copy kind (k, v[, ks, vs])
    scratch.append(pltpu.SemaphoreType.DMA((4 if quant else 2, 2)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nh),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    outs = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=bool(interpret),
    )(pos.astype(jnp.int32), page_table.astype(jnp.int32), *operands)
    if return_visits:
        return outs[0], outs[1]
    return outs[0]
