"""Authored Pallas TPU fused layer-norm kernel (forward + analytic backward).

Counterpart of the reference's fused layernorm CUDA kernels
(`paddle/phi/kernels/fusion/` / `paddle/fluid/operators/fused/fused_layernorm_*`):
one pass over each row computes mean/rstd and the normalized output; the
backward kernel computes dx in one pass plus per-block dgamma/dbeta partials
that a cheap XLA reduction finishes off.

Rows are processed in blocks of ``block_rows`` so the (rows, D) problem tiles
onto the VPU; all statistics are f32 regardless of input dtype.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, mu_ref, rs_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mu = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd * g_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mu_ref[:] = mu
    rs_ref[:] = rstd


def _bwd_kernel(x_ref, g_ref, mu_ref, rs_ref, dy_ref, dx_ref, dg_ref, db_ref,
                *, n_rows, block_rows):
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    mu, rstd = mu_ref[:], rs_ref[:]
    # rows past n_rows are block padding: their dy/xhat hold garbage that must
    # not leak into the dgamma/dbeta partial sums
    row = pl.program_id(0) * block_rows + jax.lax.broadcasted_iota(
        jnp.int32, (x.shape[0], 1), 0)
    valid = row < n_rows
    dy = jnp.where(valid, dy, 0.0)
    xhat = jnp.where(valid, (x - mu) * rstd, 0.0)
    wdy = dy * g
    c1 = jnp.mean(wdy, axis=1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=1, keepdims=True)
    dx = (wdy - c1 - xhat * c2) * rstd
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dg_ref[:] = jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[:] = jnp.sum(dy, axis=0, keepdims=True)


def _fwd(x, gamma, beta, eps, block_rows, interpret):
    n, d = x.shape
    block_rows = min(block_rows, n)
    grid = (pl.cdiv(n, block_rows),)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, gamma.reshape(1, d), beta.reshape(1, d))


def _bwd(x, gamma, mu, rstd, dy, block_rows, interpret):
    n, d = x.shape
    block_rows = min(block_rows, n)
    nb = pl.cdiv(n, block_rows)
    dx, dg_part, db_part = pl.pallas_call(
        functools.partial(_bwd_kernel, n_rows=n, block_rows=block_rows),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x.dtype),
            jax.ShapeDtypeStruct((nb, d), jnp.float32),
            jax.ShapeDtypeStruct((nb, d), jnp.float32),
        ],
        interpret=interpret,
    )(x, gamma.reshape(1, d), mu, rstd, dy)
    return dx, dg_part.sum(0), db_part.sum(0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ln(x, gamma, beta, eps, block_rows, interpret):
    y, _, _ = _fwd(x, gamma, beta, eps, block_rows, interpret)
    return y


def _ln_fwd(x, gamma, beta, eps, block_rows, interpret):
    y, mu, rstd = _fwd(x, gamma, beta, eps, block_rows, interpret)
    return y, (x, gamma, mu, rstd)


def _ln_bwd(eps, block_rows, interpret, res, dy):
    x, gamma, mu, rstd = res
    dx, dg, db = _bwd(x, gamma, mu, rstd, dy, block_rows, interpret)
    return dx, dg.astype(gamma.dtype), db.astype(gamma.dtype)


_ln.defvjp(_ln_fwd, _ln_bwd)


def fused_layer_norm(x, gamma, beta, eps=1e-5, *, block_rows=256,
                     interpret=None):
    """Fused layernorm over the last axis. x: [..., D] jax array."""
    if interpret is None:
        from paddle_tpu.kernels.pallas._compat import default_interpret
        interpret = default_interpret()
    shape = x.shape
    d = shape[-1]
    out = _ln(x.reshape(-1, d), gamma, beta, float(eps), int(block_rows),
              bool(interpret))
    return out.reshape(shape)
