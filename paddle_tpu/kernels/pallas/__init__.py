"""In-repo authored Pallas TPU kernels.

The counterpart of the reference's hand-written fused CUDA kernels
(`paddle/phi/kernels/fusion/`, `paddle/fluid/operators/fused/`): where the
reference writes .cu files per op, this framework authors Mosaic-compiled
Pallas kernels for the ops XLA does not already fuse optimally.

Kernels:
- :mod:`flash_attention` — online-softmax attention forward
  (≈ `fused_attention_op.cu` but flash; the reference has NO flash kernel,
  SURVEY §5.7).
- :mod:`paged_attention` — ragged paged-attention decode step (arxiv
  2604.15464): grid over (sequence, head), double-buffered page DMA, page
  loop bounded by each sequence's true length. The serving engine's hot
  kernel (`FLAGS_tpu_paged_impl`).
- :mod:`prefill_attention` — the ragged PREFILL twin (r15): grid over
  (chunk-row block, head), scalar-prefetched (start, valid), page walk
  bounded by the request's true uncached tail — chunked prefill, prefix
  tails, and the PTKS1 prefill-worker stream all ride it
  (`FLAGS_tpu_prefill_impl`, selection in `kernels/registry.py`).
- :mod:`fused_layernorm` — single-pass layernorm fwd + analytic bwd
  (≈ `fused_layernorm` kernels in `phi/kernels/fusion/`).
- :mod:`rotary` — fused rotary position embedding
  (≈ `fused_rope` in newer reference branches).

All kernels run under ``interpret=True`` on CPU for tests; on TPU they compile
through Mosaic.
"""
from paddle_tpu.kernels.pallas.flash_attention import flash_attention  # noqa: F401
from paddle_tpu.kernels.pallas.fused_layernorm import fused_layer_norm  # noqa: F401
from paddle_tpu.kernels.pallas.rotary import apply_rotary_emb  # noqa: F401
from paddle_tpu.kernels.pallas import paged_attention as paged_attention  # noqa: F401,PLC0414
from paddle_tpu.kernels.pallas import prefill_attention as prefill_attention  # noqa: F401,PLC0414
