"""Authored Pallas TPU fused rotary-position-embedding kernel.

Counterpart of the reference's fused rope CUDA path (the reference snapshot
applies rotary embeddings with unfused elementwise ops; newer branches ship
`fused_rope`). One kernel applies the rotation to Q and K simultaneously so
the cos/sin tables are read from VMEM once per block.

Convention: pairs are (x[..., :D/2], x[..., D/2:]) (GPT-NeoX style, matching
`paddle_tpu.models.gpt`'s rotary helper).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _rope_kernel(q_ref, k_ref, cos_ref, sin_ref, qo_ref, ko_ref):
    cos = cos_ref[0].astype(jnp.float32)          # [block_s, D/2]
    sin = sin_ref[0].astype(jnp.float32)

    def rot(x):
        x = x.astype(jnp.float32)
        half = x.shape[-1] // 2
        x1, x2 = x[..., :half], x[..., half:]
        return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                               axis=-1)

    qo_ref[0] = rot(q_ref[0]).astype(qo_ref.dtype)
    ko_ref[0] = rot(k_ref[0]).astype(ko_ref.dtype)


def apply_rotary_emb(q, k, cos, sin, *, block_s=256, interpret=None):
    """Apply rotary embeddings to q and k in one fused pass.

    q/k: [B, H, S, D]; cos/sin: [S, D/2]. Returns (q_rot, k_rot).
    """
    if interpret is None:
        from paddle_tpu.kernels.pallas._compat import default_interpret
        interpret = default_interpret()
    b, h, s, d = q.shape
    block_s = min(block_s, s)
    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * h, s, d)
    grid = (b * h, pl.cdiv(s, block_s))
    qo, ko = pl.pallas_call(
        _rope_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_s, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_s, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_s, d // 2), lambda bh, i: (0, i, 0)),
            pl.BlockSpec((1, block_s, d // 2), lambda bh, i: (0, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_s, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_s, d), lambda bh, i: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qf.shape, q.dtype),
            jax.ShapeDtypeStruct(kf.shape, k.dtype),
        ],
        interpret=interpret,
    )(qf, kf, cos[None], sin[None])
    return qo.reshape(b, h, s, d), ko.reshape(b, h, s, d)
