"""Authored Pallas TPU ragged PREFILL attention kernel (the prefill half
of arxiv 2604.15464 — the decode half is `pallas/paged_attention.py`).

The XLA prefill arm (`kernels/paged_attention.py::_xla_prefill_attention`,
the math `models/gpt.py::prefill_chunk_step` always ran) gathers the FULL
padded ``[pages_per_slot * page_size, nh, dh]`` K and V windows per layer
per chunk — HBM traffic and FLOPs scale with the slot's CAPACITY and the
chunk's pow-2 bucket, not with the request's true uncached tail. Since
chunked prefill (PR 6) made bucketed prefill the dominant non-decode cost
and the PR 13 prefill-worker tier runs nothing else, this kernel is the
drop-in the registry routes to:

- **grid over (chunk-row block, head)** — one grid cell owns a
  ``[block_q, dh]`` slice of the chunk's queries for one head;
- **scalar-prefetched per-slot lengths** — ``start`` (absolute position of
  the chunk's first token) and ``valid`` (true token count in this chunk)
  arrive via scalar prefetch with the page-table row, so every bound below
  is known before the body runs;
- **length-aware stop** — a q block whose rows all sit past ``valid``
  (bucket padding) visits ZERO pages; an active block's page loop runs
  ``ceil((start + last_active_row + 1) / page_size)`` iterations — compute
  AND DMA scale with the request's true context (cached prefix + real
  tail), never with ``pages_per_slot`` or the pow-2 bucket. Per-cell trip
  counts are a kernel output (``return_visits``) so tests assert the
  scaling;
- **double-buffered page DMA** — the K/V pools stay in HBM
  (``memory_space=ANY``); each cell streams one ``[page_size, dh]`` page
  slice at a time into a two-slot VMEM scratch, next page's DMA in flight
  while the current page is on the MXU, folding into an f32 online softmax
  — the same rhythm as the decode kernel;
- **int8-KV scale slices ride the same operands** — under ``k_scale``/
  ``v_scale`` the pools are int8 and each visited page's ``[page_size]``
  f32 scale slice DMAs in the same double-buffered rhythm; the dequant is
  in-register after the copy lands, so HBM traffic is the int8 bytes.

Numerics match the XLA arm (f32 scores, absolute-position mask, f32
softmax) to token identity — parity in interpret mode off-TPU is enforced
by tests/test_prefill_pallas.py; selection lives in the kernel registry
(``FLAGS_tpu_prefill_impl``, `kernels/registry.py`).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def block_visits(start, valid, row0, block_q, page_size):
    """Trip count of one q block's page loop — the length-aware stop. A
    block with no row < ``valid`` visits zero pages; otherwise it walks
    ``ceil((start + last_active_row_in_block + 1) / page_size)`` pages."""
    nrows = jnp.clip(valid - row0, 0, block_q)
    last_pos = start + row0 + nrows - 1
    return jnp.where(nrows > 0, (last_pos + page_size) // page_size, 0)


def default_block_q(c: int) -> int:
    """Query rows per grid cell: the whole chunk for the small chunk sizes
    serving uses (<= 256 keeps the [block_q, page_size] score tile modest),
    capped so giant one-shot buckets still tile."""
    return min(int(c), 256)


def _prefill_kernel(meta_ref, pt_ref, q_ref, k_hbm, v_hbm, o_ref, *rest,
                    page_size, block_q, scale, quant=False,
                    has_visits=False):
    # one grid cell per (q block i, head h): q_ref [block_q, 1, dh] in
    # VMEM, k_hbm/v_hbm the full [num_pages, page_size, nh, dh] pools in
    # HBM, meta (start, valid) + the page-table row scalar-prefetched into
    # SMEM. Operand unpacking mirrors the decode kernel: under ``quant``
    # two scale pools ride extra HBM operands + scale VMEM buffers, and
    # the visits output exists only under ``return_visits`` (static flag,
    # never inferred from argument counts).
    if quant:
        ks_hbm, vs_hbm, o_ref, *rest = o_ref, rest[0], rest[1], *rest[2:]
    else:
        ks_hbm = vs_hbm = None
    if has_visits:
        visits_ref, rest = rest[0], rest[1:]
    else:
        visits_ref = None
    if quant:
        kbuf, vbuf, ksbuf, vsbuf, sem = rest
    else:
        kbuf, vbuf, sem = rest
        ksbuf = vsbuf = None
    i = pl.program_id(0)
    h = pl.program_id(1)
    start = meta_ref[0]
    valid = meta_ref[1]
    row0 = i * block_q
    nrows = jnp.clip(valid - row0, 0, block_q)     # active rows this block
    npages = block_visits(start, valid, row0, block_q, page_size)
    if visits_ref is not None:
        visits_ref[0, 0] = npages      # the loop bound, exported for tests

    def dma(slot, j):
        # page j of this sequence: DMA this head's [page_size, dh] slice
        # (plus its [page_size] scale slice when the pool is int8)
        pg = pt_ref[j]
        copies = [pltpu.make_async_copy(k_hbm.at[pg, :, h, :], kbuf.at[slot],
                                        sem.at[0, slot]),
                  pltpu.make_async_copy(v_hbm.at[pg, :, h, :], vbuf.at[slot],
                                        sem.at[1, slot])]
        if quant:
            copies += [pltpu.make_async_copy(ks_hbm.at[pg, :, h],
                                             ksbuf.at[slot],
                                             sem.at[2, slot]),
                       pltpu.make_async_copy(vs_hbm.at[pg, :, h],
                                             vsbuf.at[slot],
                                             sem.at[3, slot])]
        return copies

    @pl.when(npages > 0)
    def _():                           # a fully-padded block DMAs nothing
        for c in dma(0, 0):
            c.start()

    q = q_ref[:, 0, :].astype(jnp.float32) * scale         # [block_q, dh]
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    pos = start + row0 + rows                              # [block_q, 1]
    row_ok = rows < nrows

    def body(j, carry):
        m, l, acc = carry
        slot = jax.lax.rem(j, jnp.int32(2))
        nslot = jax.lax.rem(j + jnp.int32(1), jnp.int32(2))

        @pl.when(j + jnp.int32(1) < npages)
        def _():                       # overlap: next page's DMA in flight
            for c in dma(nslot, j + jnp.int32(1)):
                c.start()

        for c in dma(slot, j):
            c.wait()
        k = kbuf[slot].astype(jnp.float32)                 # [ps, dh]
        v = vbuf[slot].astype(jnp.float32)
        if quant:
            # dequantize in-register AFTER the page copy: the DMA moved
            # int8 bytes; only the VMEM-resident working tile widens
            k = k * ksbuf[slot][:, None]
            v = v * vsbuf[slot][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        kpos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        # absolute-position causality: query at position p sees keys 0..p
        # — within-chunk future tokens mask out exactly like unwritten
        # pages; padded rows (>= valid) contribute nothing
        s = jnp.where((kpos <= pos) & row_ok, s, NEG_INF)  # [block_q, ps]
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    dh = q_ref.shape[-1]
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    a0 = jnp.zeros((block_q, dh), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, npages, body, (m0, l0, a0))
    out = jnp.where(row_ok, acc / jnp.maximum(l, 1e-30), 0.0)
    o_ref[:, 0, :] = out.astype(o_ref.dtype)


def prefill_attention(q, k_pages, v_pages, page_table, start, valid, *,
                      interpret=None, return_visits=False, block_q=None,
                      k_scale=None, v_scale=None):
    """One CHUNK of ragged prefill attention for ONE sequence over paged
    K/V (the chunk's own K/V already written to its pages):

    q          : [C, nh, dh] — the chunk's queries (rows >= valid are
                 bucket padding; their output is zeroed)
    k_pages    : [num_pages, page_size, nh, dh] (one layer)
    v_pages    : [num_pages, page_size, nh, dh]
    page_table : [pages_per_slot] int32 — THIS sequence's page row
    start      : scalar int32 — absolute position of q[0]
    valid      : scalar int32 — true token count in this chunk
    k_scale/v_scale : optional [num_pages, page_size, nh] f32 (int8 pools)
    returns    : [C, nh, dh] in q.dtype; with ``return_visits=True`` also
                 the per-(q block, head) page-loop trip counts
                 [ceil(C / block_q), nh] int32 — the ragged-stop proof.

    ``interpret=None`` auto-selects the Pallas interpreter off-TPU (CPU
    parity tests); on TPU the kernel compiles through Mosaic.
    """
    if interpret is None:
        from paddle_tpu.kernels.pallas._compat import default_interpret
        interpret = default_interpret()
    quant = k_scale is not None
    c, nh, dh = q.shape
    ps = k_pages.shape[1]
    bq = default_block_q(c) if block_q is None else min(int(block_q), c)
    nq = pl.cdiv(c, bq)
    scale = 1.0 / (dh ** 0.5)
    kern = functools.partial(_prefill_kernel, page_size=ps, block_q=bq,
                             scale=float(scale), quant=quant,
                             has_visits=bool(return_visits))
    out_specs = [pl.BlockSpec((bq, 1, dh), lambda i, j, *_: (i, j, 0))]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    if return_visits:
        out_specs.append(pl.BlockSpec((1, 1), lambda i, j, *_: (i, j)))
        out_shape.append(jax.ShapeDtypeStruct((nq, nh), jnp.int32))
    in_specs = [
        pl.BlockSpec((bq, 1, dh), lambda i, j, *_: (i, j, 0)),
        pl.BlockSpec(memory_space=pltpu.ANY),         # K pool stays in HBM
        pl.BlockSpec(memory_space=pltpu.ANY),         # V pool stays in HBM
    ]
    scratch = [
        pltpu.VMEM((2, ps, dh), k_pages.dtype),       # K double buffer
        pltpu.VMEM((2, ps, dh), v_pages.dtype),       # V double buffer
    ]
    operands = [q, k_pages, v_pages]
    if quant:
        in_specs += [pl.BlockSpec(memory_space=pltpu.ANY),   # K scales
                     pl.BlockSpec(memory_space=pltpu.ANY)]   # V scales
        scratch += [pltpu.VMEM((2, ps), jnp.float32),
                    pltpu.VMEM((2, ps), jnp.float32)]
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    # semaphore rows: one per in-flight copy kind (k, v[, ks, vs])
    scratch.append(pltpu.SemaphoreType.DMA((4 if quant else 2, 2)))
    meta = jnp.stack([jnp.asarray(start, jnp.int32),
                      jnp.asarray(valid, jnp.int32)])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nq, nh),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    outs = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=bool(interpret),
    )(meta, page_table.astype(jnp.int32), *operands)
    if return_visits:
        return outs[0], outs[1]
    return outs[0]
