"""Flash attention for TPU.

Counterpart of `paddle/fluid/operators/fused/fused_attention_op.cu` — which is
non-flash (`fmha_ref.h`), so this is strictly beyond reference parity (SURVEY.md
§5.7 requires it). Strategy:

1. Pallas TPU flash kernel (jax.experimental.pallas.ops.tpu.flash_attention) when
   shapes are TPU-tileable (seq multiple of block, head_dim aligned);
2. otherwise a blockwise online-softmax attention in pure lax (still O(S) memory
   via jax.checkpoint-friendly scan), which XLA fuses well.

Layout note: paddle uses [B, S, H, D]; the pallas op uses [B, H, S, D].
"""
from __future__ import annotations

import contextlib
import functools
import math

import jax
import jax.numpy as jnp

_PALLAS_OK = None


def _try_pallas():
    global _PALLAS_OK, _fa_mod
    if _PALLAS_OK is None:
        try:
            from jax.experimental.pallas.ops.tpu import flash_attention as _m
            _fa_mod = _m
            _PALLAS_OK = jax.default_backend() == "tpu"
        except Exception:
            _PALLAS_OK = False
    return _PALLAS_OK


def _x64_off():
    """The Mosaic flash kernel mixes int32 iota with weakly-typed python ints,
    which breaks under jax_enable_x64 (paddle enables x64 globally for int64
    tensor semantics). Trace the kernel's fwd AND bwd under x64-disabled
    promotion rules; array dtypes themselves are unaffected."""
    if jax.config.jax_enable_x64:
        return jax.enable_x64(False)
    return contextlib.nullcontext()


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _pallas_flash(q, k, v, causal, sm_scale):
    out, _ = _pallas_flash_fwd(q, k, v, causal, sm_scale)
    return out


def _pallas_flash_fwd(q, k, v, causal, sm_scale):
    _try_pallas()
    bs = _fa_mod.BlockSizes.get_default(
        q.shape[0], q.shape[1], q.shape[2], k.shape[2], q.shape[3])
    with _x64_off():
        o, res = _fa_mod._flash_attention_fwd(
            q, k, v, None, None, False, causal, sm_scale, bs, False)
    return o, res


def _pallas_flash_bwd(causal, sm_scale, res, do):
    _try_pallas()
    q, k = res[0], res[1]
    bs = _fa_mod.BlockSizes.get_default(
        q.shape[0], q.shape[1], q.shape[2], k.shape[2], q.shape[3])
    with _x64_off():
        dq, dk, dv, _, _ = _fa_mod._flash_attention_bwd(
            False, causal, sm_scale, bs, False, res, do)
    return dq, dk, dv


_pallas_flash.defvjp(_pallas_flash_fwd, _pallas_flash_bwd)


def _blockwise_attention(q, k, v, causal, scale, block_k=512):
    """Online-softmax attention scanning over K blocks (lax fallback)."""
    # q,k,v: [B, H, S, D]
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    q = q * s
    nblocks = max((Sk + block_k - 1) // block_k, 1)
    pad = nblocks * block_k - Sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(B, H, nblocks, block_k, D)
    vb = v.reshape(B, H, nblocks, block_k, D)
    q_idx = jnp.arange(Sq)

    def body(carry, blk):
        m_prev, l_prev, acc = carry
        kk, vv, base = blk
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, kk,
                            preferred_element_type=jnp.float32)
        kpos = base + jnp.arange(block_k)
        valid = kpos < Sk
        if causal:
            valid = valid[None, :] & (kpos[None, :] <= (
                q_idx + (Sk - Sq))[:, None])
            logits = jnp.where(valid[None, None], logits, -jnp.inf)
        else:
            logits = jnp.where(valid[None, None, None], logits, -jnp.inf)
        m_cur = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        # guard fully-masked rows
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(logits - m_safe[..., None])
        p = jnp.where(jnp.isfinite(logits), p, 0.0)
        alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vv.dtype), vv,
            preferred_element_type=jnp.float32)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)
    acc0 = jnp.zeros((B, H, Sq, D), jnp.float32)
    bases = jnp.arange(nblocks) * block_k
    (m, l, acc), _ = jax.lax.scan(
        body, (m0, l0, acc0),
        (jnp.moveaxis(kb, 2, 0), jnp.moveaxis(vb, 2, 0), bases))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def flash_attention_fn(causal=False, scale=None):
    """Returns a pure fn(q, k, v) on paddle-layout [B, S, H, D] tensors."""

    def fn(q, k, v):
        from paddle_tpu.framework.flags import flag_value
        # -> [B, H, S, D]
        qt = jnp.swapaxes(q, 1, 2)
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        S, D = qt.shape[2], qt.shape[3]
        # The Mosaic kernel is opt-in: profiled on the current v5e runtime, its
        # bwd_dkv/bwd_dq kernels are ~4x slower than XLA's fused attention at
        # GPT-2 shapes (see BENCH notes). XLA's blockwise online-softmax keeps
        # O(S) memory for long sequences; plain fused attention wins below 2k.
        use_pallas = (flag_value("tpu_use_mosaic_flash") and _try_pallas()
                      and S % 128 == 0 and D % 64 == 0
                      and qt.dtype in (jnp.float32, jnp.bfloat16))
        if use_pallas:
            sm = scale if scale is not None else 1.0 / math.sqrt(D)
            out = _pallas_flash(qt, kt, vt, causal, sm)
        else:
            out = _blockwise_attention(qt, kt, vt, causal, scale)
        return jnp.swapaxes(out, 1, 2)

    return fn
