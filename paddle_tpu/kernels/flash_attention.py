"""Flash attention for TPU.

Counterpart of `paddle/fluid/operators/fused/fused_attention_op.cu` — which is
non-flash (`fmha_ref.h`), so this is strictly beyond reference parity (SURVEY.md
§5.7 requires it). Strategy:

1. Pallas TPU flash kernel (jax.experimental.pallas.ops.tpu.flash_attention) when
   shapes are TPU-tileable (seq multiple of block, head_dim aligned);
2. otherwise a blockwise online-softmax attention in pure lax (still O(S) memory
   via jax.checkpoint-friendly scan), which XLA fuses well.

Layout note: paddle uses [B, S, H, D]; the pallas op uses [B, H, S, D].
"""
from __future__ import annotations

import contextlib
import functools
import math

import jax
import jax.numpy as jnp

_PALLAS_OK = None


def _try_pallas():
    global _PALLAS_OK, _fa_mod
    if _PALLAS_OK is None:
        try:
            from jax.experimental.pallas.ops.tpu import flash_attention as _m
            _fa_mod = _m
            _PALLAS_OK = jax.default_backend() == "tpu"
        except Exception:
            _PALLAS_OK = False
    return _PALLAS_OK


def _x64_off():
    """Pallas kernels mix int32 iota with weakly-typed python ints, which
    breaks under jax_enable_x64 (paddle enables x64 globally for int64 tensor
    semantics) — trace them under x64-disabled promotion rules. Single shared
    helper lives in autograd (also used by apply(x64_off=True))."""
    from paddle_tpu.core.autograd import _x64_off_scope
    return _x64_off_scope()


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _pallas_flash(q, k, v, causal, sm_scale):
    out, _ = _pallas_flash_fwd(q, k, v, causal, sm_scale)
    return out


def _pallas_flash_fwd(q, k, v, causal, sm_scale):
    _try_pallas()
    bs = _fa_mod.BlockSizes.get_default(
        q.shape[0], q.shape[1], q.shape[2], k.shape[2], q.shape[3])
    with _x64_off():
        o, res = _fa_mod._flash_attention_fwd(
            q, k, v, None, None, False, causal, sm_scale, bs, False)
    return o, res


def _pallas_flash_bwd(causal, sm_scale, res, do):
    _try_pallas()
    q, k = res[0], res[1]
    bs = _fa_mod.BlockSizes.get_default(
        q.shape[0], q.shape[1], q.shape[2], k.shape[2], q.shape[3])
    with _x64_off():
        dq, dk, dv, _, _ = _fa_mod._flash_attention_bwd(
            False, causal, sm_scale, bs, False, res, do)
    return dq, dk, dv


_pallas_flash.defvjp(_pallas_flash_fwd, _pallas_flash_bwd)


def _blockwise_attention(q, k, v, causal, scale, block_k=512):
    """Online-softmax attention scanning over K blocks (lax fallback)."""
    # q,k,v: [B, H, S, D]
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    q = q * s
    nblocks = max((Sk + block_k - 1) // block_k, 1)
    pad = nblocks * block_k - Sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(B, H, nblocks, block_k, D)
    vb = v.reshape(B, H, nblocks, block_k, D)
    q_idx = jnp.arange(Sq)

    def body(carry, blk):
        m_prev, l_prev, acc = carry
        kk, vv, base = blk
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, kk,
                            preferred_element_type=jnp.float32)
        kpos = base + jnp.arange(block_k)
        valid = kpos < Sk
        if causal:
            valid = valid[None, :] & (kpos[None, :] <= (
                q_idx + (Sk - Sq))[:, None])
            logits = jnp.where(valid[None, None], logits, -jnp.inf)
        else:
            logits = jnp.where(valid[None, None, None], logits, -jnp.inf)
        m_cur = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        # guard fully-masked rows
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(logits - m_safe[..., None])
        p = jnp.where(jnp.isfinite(logits), p, 0.0)
        alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vv.dtype), vv,
            preferred_element_type=jnp.float32)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)
    acc0 = jnp.zeros((B, H, Sq, D), jnp.float32)
    bases = jnp.arange(nblocks) * block_k
    (m, l, acc), _ = jax.lax.scan(
        body, (m0, l0, acc0),
        (jnp.moveaxis(kb, 2, 0), jnp.moveaxis(vb, 2, 0), bases))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


_SPLASH_CACHE: dict = {}


def _splash_kernel(n_heads, S, causal):
    """Cached Splash (Pallas) MHA kernel — the production TPU flash attention.
    Created under ensure_compile_time_eval so the precomputed mask-info arrays
    stay concrete even when first touched inside an abstract capture probe."""
    key = (n_heads, S, causal)
    if key not in _SPLASH_CACHE:
        from jax.experimental.pallas.ops.tpu.splash_attention import (
            splash_attention_kernel as sk, splash_attention_mask as sm)
        with jax.ensure_compile_time_eval(), _x64_off():
            mask = sm.MultiHeadMask(
                [sm.CausalMask((S, S)) if causal else sm.FullMask((S, S))
                 for _ in range(n_heads)])
            _SPLASH_CACHE[key] = sk.make_splash_mha(
                mask, head_shards=1, q_seq_shards=1)
    return _SPLASH_CACHE[key]


def _splash_attention(q, k, v, causal, scale):
    """q,k,v: [B,H,S,D]; caller must hold an x64-off scope across fwd+bwd
    traces (see autograd.apply(x64_off=True))."""
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    kern = _splash_kernel(q.shape[1], q.shape[2], causal)
    return jax.vmap(kern)((q * s).astype(q.dtype), k, v)


def _qblocks(S):
    """Static q-block size (unrolled python loop — lax.scan variants hit
    pathological compile paths on the current TPU toolchain).

    256 measured best on v5e (round-4 sweep, GPT-2s B16/S1024, fwd+bwd
    per-12-layers: bq=1024 74.6 ms, 512 54.7, 256 48.5, 128 50.3): small
    blocks make the causal ``kend`` truncation real — with bq == S the whole
    [S, S] logits block is computed then half masked away, while bq=256 skips
    the upper-triangular blocks' FLOPs and HBM traffic entirely. Whole-step
    effect: 101.0k -> 120.7k tok/s (MFU 0.383 -> 0.458). Above 4k the block
    size grows back to 1024 to bound the unrolled block count (compile
    time)."""
    return min(256, S) if S <= 4096 else 1024


# bwd may use a different q-block than fwd: each bwd block pays a padded
# dk/dv accumulation over the FULL K length, so fewer/larger blocks trade
# upper-triangular logit FLOPs for less accumulator traffic. Swept r5
# (GPT-2s B16/S1024 whole step): bwd 512 -> 149.2 ms, 128 -> 152.7 ms vs
# 130.5 ms at the shared 256 — the split LOSES both ways; 256 is a sharp
# joint optimum. None = same as fwd (kept as an experiment hook).
_QBLOCKS_BWD = None


def _qblocks_bwd(S):
    return _QBLOCKS_BWD if _QBLOCKS_BWD else _qblocks(S)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _xla_flash(q, k, v, causal, scale):
    out, _ = _xla_flash_fwd(q, k, v, causal, scale)
    return out


def _block_logits(qb, k, scale):
    # [B,H,Bq,D] x [B,H,Sk,D] -> [B,H,Bq,Sk]; bf16 inputs materialize bf16
    # logits (halves the S^2 HBM traffic, reductions still accumulate f32)
    acc = jnp.bfloat16 if qb.dtype == jnp.bfloat16 else jnp.float32
    return jax.lax.dot_general(
        qb * scale, k, (((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=acc)


def _causal_mask(bq, kend, q0, sq_total, sk_total):
    """kend: K prefix length kept for this q block (absolute positions 0..kend);
    the causal offset is measured against the FULL k length (decode caches make
    Sk > Sq)."""
    qpos = q0 + jnp.arange(bq)
    kpos = jnp.arange(kend)
    return kpos[None, :] <= (qpos[:, None] + (sk_total - sq_total))


def _xla_flash_fwd(q, k, v, causal, scale):
    """Flash-style attention in pure XLA: the [S,S] probability matrix exists
    only transiently inside each q-block; residuals are (q, k, v, out, lse).
    Counterpart of the reference's fused_attention fmha path, but online-safe
    (ref `operators/fused/fused_attention_op.cu` is non-flash)."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    bq = _qblocks(Sq)
    outs, lses = [], []
    for q0 in range(0, Sq, bq):
        qb = q[:, :, q0:q0 + bq]
        # causal: later K positions can't be attended by this q block — slice
        # them off entirely (real FLOP/traffic saving, not just masking).
        # Clamp to >= 1: Sq > Sk causal rows with no visible key keep the
        # degenerate single-block behavior (all-masked -> uniform weights)
        kend = min(max(q0 + bq + (Sk - Sq), 1), Sk) if causal else Sk
        kb, vb = k[:, :, :kend], v[:, :, :kend]
        logits = _block_logits(qb, kb, s)                   # bf16 [B,H,Bq,kend]
        if causal:
            m = _causal_mask(qb.shape[2], kend, q0, Sq, Sk)
            logits = jnp.where(m[None, None], logits,
                               jnp.asarray(-1e30, logits.dtype))
        mx = jnp.max(logits, axis=-1, keepdims=True)        # exact in bf16
        z = logits.astype(jnp.float32) - mx.astype(jnp.float32)
        l = jnp.sum(jnp.exp(z), axis=-1, keepdims=True)     # f32 accumulation
        p = jnp.exp(z).astype(v.dtype)                      # bf16 for the MXU
        acc = jax.lax.dot_general(
            p, vb, (((3,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32)
        outs.append((acc / l).astype(q.dtype))              # normalize post-dot
        lses.append((mx.astype(jnp.float32) + jnp.log(l))[..., 0])
    out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=2)
    lse = lses[0] if len(lses) == 1 else jnp.concatenate(lses, axis=2)
    return out, (q, k, v, out, lse)


def _xla_flash_bwd(causal, scale, res, do):
    q, k, v, out, lse = res
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    bq = _qblocks_bwd(Sq)
    dqs = []
    dk = jnp.zeros((B, H, Sk, D), jnp.float32)
    dv = jnp.zeros((B, H, Sk, D), jnp.float32)
    for q0 in range(0, Sq, bq):
        qb = q[:, :, q0:q0 + bq]
        dob = do[:, :, q0:q0 + bq]
        ob = out[:, :, q0:q0 + bq]
        lseb = lse[:, :, q0:q0 + bq]
        kend = min(max(q0 + bq + (Sk - Sq), 1), Sk) if causal else Sk
        kb, vb = k[:, :, :kend], v[:, :, :kend]
        logits = _block_logits(qb, kb, s)
        if causal:
            m = _causal_mask(qb.shape[2], kend, q0, Sq, Sk)
            logits = jnp.where(m[None, None], logits,
                               jnp.asarray(-1e30, logits.dtype))
        # p recomputed from lse: [B,H,Bq,kend] bf16, never a residual
        p = jnp.exp(logits.astype(jnp.float32) -
                    lseb[..., None]).astype(v.dtype)
        # dv += p^T do ; dp = do v^T ; ds = p*(dp - di) ; dq = ds k ; dk += ds^T q
        dvc = jax.lax.dot_general(
            p, dob, (((2,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            dob, vb, (((3,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=(jnp.bfloat16 if v.dtype == jnp.bfloat16
                                    else jnp.float32))
        di = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32),
                     axis=-1, keepdims=True)
        ds = (p.astype(jnp.float32) *
              (dp.astype(jnp.float32) - di)).astype(q.dtype)
        dqs.append(jax.lax.dot_general(
            ds, kb, (((3,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32) * s)
        dkc = jax.lax.dot_general(
            ds, qb, (((2,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32) * s
        if kend == Sk:
            dk = dk + dkc
            dv = dv + dvc
        else:
            pad = ((0, 0), (0, 0), (0, Sk - kend), (0, 0))
            dk = dk + jnp.pad(dkc, pad)
            dv = dv + jnp.pad(dvc, pad)
    dq = dqs[0] if len(dqs) == 1 else jnp.concatenate(dqs, axis=2)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_xla_flash.defvjp(_xla_flash_fwd, _xla_flash_bwd)


def _dense_attention(q, k, v, causal, scale):
    """Full-materialization SDPA: the [B, H, Sq, Sk] scores exist in HBM
    (bf16 when inputs are bf16) and XLA autodiffs it. At moderate S the
    S^2 tensor fits easily and the single fused softmax beats chunked
    flash's loop overhead — the autotuner decides per shape."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    acc = jnp.bfloat16 if q.dtype == jnp.bfloat16 else jnp.float32
    logits = jax.lax.dot_general(
        q * s, k, (((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=acc)
    if causal:
        qpos = jnp.arange(Sq)
        kpos = jnp.arange(Sk)
        mask = kpos[None, :] <= (qpos[:, None] + (Sk - Sq))
        logits = jnp.where(mask[None, None], logits,
                           jnp.asarray(-1e30, logits.dtype))
    p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(v.dtype)
    return jax.lax.dot_general(
        p, v, (((3,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=v.dtype)


def _impl_call(impl, qt, kt, vt, causal, scale, tileable):
    """Execute one named implementation on [B, H, S, D] arrays."""
    if impl == "dense":
        return _dense_attention(qt, kt, vt, causal, scale)
    if impl == "splash" and tileable:
        return _splash_attention(qt, kt, vt, causal, scale)
    if impl == "mosaic" and tileable:
        sm = scale if scale is not None else 1.0 / math.sqrt(qt.shape[-1])
        return _pallas_flash(qt, kt, vt, causal, sm)
    if impl == "authored":
        # the in-repo Pallas kernels (kernels/pallas/flash_attention.py),
        # forward AND backward
        from paddle_tpu.kernels.pallas import flash_attention as _authored
        return _authored(qt, kt, vt, causal=causal, sm_scale=scale)
    return _xla_flash(qt, kt, vt, causal, scale)


def flash_attention_fn(causal=False, scale=None):
    """Returns a pure fn(q, k, v) on paddle-layout [B, S, H, D] tensors."""

    def fn(q, k, v):
        from paddle_tpu.framework.flags import flag_value
        from paddle_tpu.kernels import registry
        # -> [B, H, S, D]
        qt = jnp.swapaxes(q, 1, 2)
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        S, D = qt.shape[2], qt.shape[3]
        tileable = (_try_pallas() and S % 128 == 0 and D % 64 == 0
                    and S == kt.shape[2]
                    and qt.dtype in (jnp.float32, jnp.bfloat16))

        def winner():
            # measured selection, cached per (backend, shape, dtype,
            # causal) — ref phi/kernels/autotune. Runs eagerly at trace
            # time; the winner string is baked into this trace (the
            # program cache keys on the flag + shapes, so retunes key new
            # programs).
            from paddle_tpu.kernels.autotune import flash_winner
            return flash_winner(
                tuple(qt.shape), tuple(kt.shape), qt.dtype, causal,
                tileable,
                lambda i, q_, k_, v_: _impl_call(i, q_, k_, v_, causal,
                                                 scale, tileable))

        impl = registry.dispatch(
            "flash_attention", forced=flag_value("tpu_flash_impl"),
            ctx={"tileable": tileable, "shape_q": tuple(qt.shape),
                 "shape_k": tuple(kt.shape)},
            winner=winner)
        out = _impl_call(impl, qt, kt, vt, causal, scale, tileable)
        return jnp.swapaxes(out, 1, 2)

    return fn
