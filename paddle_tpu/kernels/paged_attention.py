"""Paged KV-cache attention — the serving-side cache layout (arxiv 2604.15464).

Dense decode caches ([B, L, nh, dh] per layer, one slab per sequence) waste
HBM on short sequences and force one compiled program per (B, L) shape. The
paged layout stores tokens in fixed-size PAGES:

    k_pages, v_pages : [num_layers, num_pages, page_size, num_heads, head_dim]

and each sequence owns an ordered list of page indices (the host-side page
table, padded to ``pages_per_slot``). Token position ``t`` of a sequence
lives at ``(page_table[t // page_size], t % page_size)``. Pages are
allocated/freed by the engine's host-side allocator as sequences join and
retire, so B live sequences of wildly different lengths share one fixed-shape
pool — the decode program never changes shape and never recompiles.

`paged_attention` is a DISPATCH SWITCH over two implementations with one
contract (token-identical output, enforced by parity tests):

- **xla** — the JAX-native reference: gather each sequence's pages into a
  [B, Lmax] window, masked f32-softmax attention. Correct everywhere, but
  HBM traffic and FLOPs scale with the pool's capacity (`pages_per_slot`),
  not the live lengths.
- **pallas** — the authored ragged paged-attention kernel
  (`kernels/pallas/paged_attention.py`): grid over (sequence, head),
  double-buffered page DMA, page loop bounded by ``ceil((pos+1)/page_size)``
  so traffic scales with each sequence's true length.

``FLAGS_tpu_paged_impl`` picks: ``auto`` (measured winner per signature on
real TPU via the kernel registry + `kernels/autotune.py`, xla elsewhere —
backend viability is decided by NAME/probe, `kernels/pallas/_compat.py`),
``xla``, or ``pallas`` (interpret mode off-TPU: parity tests only). Every
selection routes through `kernels/registry.py::dispatch` and is counted
per program build in ``kernel.dispatch.paged_attention.{xla|pallas}``
(plus the pre-registry alias ``paged_attention.impl.*``;
docs/OBSERVABILITY.md). The ragged PREFILL twin (`prefill_attention` /
`prefill_impl`) dispatches the same way under ``FLAGS_tpu_prefill_impl``
with counters ``kernel.dispatch.prefill_attention.*``.

Page 0 is RESERVED as the trash page: writes for inactive slots and
prompt-padding positions are routed there instead of being predicated out
(XLA scatters need valid indices; a dedicated spill target keeps the write
unconditional and the program shape static). Allocators must never hand out
page 0.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

# the reserved spill target for masked writes — never allocated to a sequence
TRASH_PAGE = 0

# EngineConfig.kv_dtype knob values -> page storage dtypes. "int8" pairs the
# int8 pages with a per-token-slot per-head f32 scale array ([nl, P, ps, nh])
# written by the same scatters that write the pages (docs/QUANTIZATION.md).
KV_DTYPES = {"f32": jnp.float32, "bf16": jnp.bfloat16, "int8": jnp.int8}

__all__ = ["TRASH_PAGE", "KV_DTYPES", "gather_kv", "quantize_kv",
           "dequantize_window", "gather_scales", "paged_attention",
           "prefill_attention", "prefill_impl", "token_page_coords",
           "prompt_page_coords", "chunk_page_coords", "verify_page_coords",
           "write_token_kv", "write_prompt_kv", "export_pages",
           "import_pages"]


def quantize_kv(x):
    """Per-head abs-max int8 for a K or V write of any leading shape
    ``[..., nh, dh]`` -> (int8 values ``[..., nh, dh]``, f32 scales
    ``[..., nh]``).

    The scale granularity is per TOKEN-SLOT per head — one scale for each
    (page, offset, head) cell, stored ``[nl, P, page_size, nh]`` alongside
    the pool. A single per-page scale cannot survive the engine's
    incremental writes: decode lands one token per step into a partially
    filled page, and re-scaling the page for a later token's larger abs-max
    would silently corrupt every earlier token's dequantization. One scale
    per written cell makes each write self-contained — pages (and their
    scales) are immutable once full, which is what lets the prefix cache
    share them by reference (docs/QUANTIZATION.md)."""
    from paddle_tpu.quantization.comms import absmax_int8
    return absmax_int8(x, axis=-1)


def dequantize_window(win, scales):
    """int8 gathered window ``[..., nh, dh]`` + scales ``[..., nh]`` -> f32."""
    return win.astype(jnp.float32) * scales[..., None]


def gather_kv(pages, page_table):
    """Materialize one layer's paged K (or V) into per-sequence windows.

    pages      : [num_pages, page_size, nh, dh]
    page_table : [B, pages_per_slot] int32 page indices
    returns    : [B, pages_per_slot * page_size, nh, dh]
    """
    _, ps, nh, dh = pages.shape
    b, maxp = page_table.shape
    return pages[page_table].reshape(b, maxp * ps, nh, dh)


def gather_scales(scales, page_table):
    """[num_pages, page_size, nh] scales -> [B, Lmax, nh] per-slot windows
    (the scale-side twin of :func:`gather_kv`)."""
    _, ps, nh = scales.shape
    b, maxp = page_table.shape
    return scales[page_table].reshape(b, maxp * ps, nh)


def _xla_paged_attention(q, k_pages, v_pages, page_table, pos,
                         k_scale=None, v_scale=None):
    """The gather + masked f32-softmax reference implementation. With
    ``k_scale``/``v_scale`` ([num_pages, page_size, nh] f32) the pages are
    int8 and dequantize in-register right after the gather — the same f32
    score/softmax math runs on the dequantized values."""
    dh = q.shape[-1]
    scale = 1.0 / (dh ** 0.5)
    k = gather_kv(k_pages, page_table).astype(jnp.float32)  # [B, Lmax, nh, dh]
    v = gather_kv(v_pages, page_table).astype(jnp.float32)
    if k_scale is not None:
        k = k * gather_scales(k_scale, page_table)[..., None]
        v = v * gather_scales(v_scale, page_table)[..., None]
    lmax = k.shape[1]
    sc = jnp.einsum("bhd,blhd->bhl", q.astype(jnp.float32) * scale, k)
    mask = jnp.arange(lmax)[None, :] <= pos[:, None]         # [B, Lmax]
    sc = jnp.where(mask[:, None, :], sc, -1e30)
    pr = jax.nn.softmax(sc, axis=-1)
    att = jnp.einsum("bhl,blhd->bhd", pr, v)
    return att.astype(q.dtype)


def _impl_call(impl, q, k_pages, v_pages, page_table, pos,
               k_scale=None, v_scale=None):
    """Execute one named implementation (also the autotuner's run_impl)."""
    if impl == "pallas":
        from paddle_tpu.kernels.pallas.paged_attention import (
            paged_attention as pallas_paged)
        return pallas_paged(q, k_pages, v_pages, page_table, pos,
                            k_scale=k_scale, v_scale=v_scale)
    return _xla_paged_attention(q, k_pages, v_pages, page_table, pos,
                                k_scale=k_scale, v_scale=v_scale)


def paged_attention(q, k_pages, v_pages, page_table, pos,
                    k_scale=None, v_scale=None):
    """One decode step of attention over paged K/V for B sequences.

    q          : [B, nh, dh] query for the CURRENT token of each sequence
    k_pages    : [num_pages, page_size, nh, dh] (one layer)
    v_pages    : [num_pages, page_size, nh, dh]
    page_table : [B, pages_per_slot] int32
    pos        : [B] int32 — position of the current token (already written
                 to the cache); attends over positions 0..pos inclusive
    returns    : [B, nh, dh] in q.dtype

    Same numerics as the dense path (f32 scores, -1e30 mask, f32 softmax):
    token-identical output is the contract, not an approximation. Dispatches
    on ``FLAGS_tpu_paged_impl`` (module docstring); the selection runs at
    trace time, so the winner string is baked into each compiled program and
    the ``paged_attention.impl.*`` counters count program builds (once per
    layer per trace), not steps.
    """
    from paddle_tpu.kernels import registry
    try:
        from paddle_tpu.framework.flags import flag_value
        forced = flag_value("tpu_paged_impl")
    except Exception:          # flags registry unavailable (early import)
        forced = "xla"

    def winner():
        from paddle_tpu.kernels.autotune import paged_winner
        run = _impl_call
        variant = ""
        if k_scale is not None:
            # int8 pools measure with synthetic unit scales (the autotuner
            # builds its own float test pages — here cast to int8) and key
            # their own winner via the variant suffix: the dequant changes
            # each candidate's arithmetic intensity. The q dtype stays a
            # REAL dtype (paged_winner builds arrays with it)
            variant = "kv-int8"

            def run(impl_, q_, kp_, vp_, pt_, pos_):
                ones = jnp.ones(kp_.shape[:3], jnp.float32)
                return _impl_call(impl_, q_, kp_.astype(jnp.int8),
                                  vp_.astype(jnp.int8), pt_, pos_,
                                  k_scale=ones, v_scale=ones)
        return paged_winner(q.shape[0], page_table.shape[1],
                            k_pages.shape[1], q.shape[1], q.shape[2],
                            q.dtype, run, variant=variant)

    impl = registry.dispatch("paged_attention", forced=forced,
                             winner=winner)
    return _impl_call(impl, q, k_pages, v_pages, page_table, pos,
                      k_scale=k_scale, v_scale=v_scale)


def _xla_prefill_attention(q, k_pages, v_pages, page_table, start, valid,
                           k_scale=None, v_scale=None):
    """The gather + absolute-position-masked f32-softmax PREFILL reference
    — exactly the math `models/gpt.py::prefill_chunk_step` always ran: the
    chunk's queries attend over ALL cached positions (previous chunks AND
    the current one) via the paged gather, masked so a query at position p
    sees keys 0..p. Traffic and FLOPs scale with the slot's capacity
    (``pages_per_slot``), which is what the Pallas arm fixes.

    q : [1, C, nh, dh] chunk queries; page_table : [pages_per_slot];
    start/valid : the chunk's absolute origin and true token count.
    ``valid`` only matters to the Pallas arm's row masking — padded rows
    here compute like the real ones (their output is never consumed).
    """
    dh = q.shape[-1]
    c = q.shape[1]
    scale = 1.0 / (dh ** 0.5)
    kk = gather_kv(k_pages, page_table[None]).astype(jnp.float32)
    vv = gather_kv(v_pages, page_table[None]).astype(jnp.float32)
    if k_scale is not None:
        kk = kk * gather_scales(k_scale, page_table[None])[..., None]
        vv = vv * gather_scales(v_scale, page_table[None])[..., None]
    lmax = kk.shape[1]
    pos = start + jnp.arange(c)
    sc = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale, kk)
    mask = jnp.arange(lmax)[None, :] <= pos[:, None]         # [C, Lmax]
    sc = jnp.where(mask[None, None], sc, -1e30)
    pr = jax.nn.softmax(sc, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", pr, vv).astype(q.dtype)


def _prefill_impl_call(impl, q, k_pages, v_pages, page_table, start, valid,
                       k_scale=None, v_scale=None):
    """Execute one named prefill impl (also the autotuner's run_impl)."""
    if impl == "pallas":
        from paddle_tpu.kernels.pallas.prefill_attention import (
            prefill_attention as pallas_prefill)
        return pallas_prefill(q[0], k_pages, v_pages, page_table, start,
                              valid, k_scale=k_scale, v_scale=v_scale)[None]
    return _xla_prefill_attention(q, k_pages, v_pages, page_table, start,
                                  valid, k_scale=k_scale, v_scale=v_scale)


def prefill_impl(chunk, pages_per_slot, page_size, nh, dh, dtype,
                 quant=False, parity=True) -> str:
    """Resolve (and COUNT) the prefill-attention impl for one program
    build — the registry is the only selector (`kernels/registry.py`;
    ``FLAGS_tpu_prefill_impl`` forces, ``auto`` measures via
    `autotune.prefill_winner`). ``parity=False`` marks a call whose XLA
    arm does NOT read the page pool (the one-shot `prefill_step` over a
    narrowing pool dtype), which drops the pallas candidate rather than
    silently changing numerics."""
    from paddle_tpu.kernels import registry
    try:
        from paddle_tpu.framework.flags import flag_value
        forced = flag_value("tpu_prefill_impl")
    except Exception:          # flags registry unavailable (early import)
        forced = "xla"

    def winner():
        from paddle_tpu.kernels.autotune import prefill_winner
        run = _prefill_impl_call
        variant = ""
        if quant:
            variant = "kv-int8"

            def run(impl_, q_, kp_, vp_, row_, start_, valid_):
                ones = jnp.ones(kp_.shape[:3], jnp.float32)
                return _prefill_impl_call(
                    impl_, q_, kp_.astype(jnp.int8), vp_.astype(jnp.int8),
                    row_, start_, valid_, k_scale=ones, v_scale=ones)
        return prefill_winner(chunk, pages_per_slot, page_size, nh, dh,
                              dtype, run, variant=variant, parity=parity)

    return registry.dispatch("prefill_attention", forced=forced,
                             ctx={"parity": parity}, winner=winner)


def prefill_attention(q, k_pages, v_pages, page_table, start, valid,
                      k_scale=None, v_scale=None):
    """One CHUNK of ragged prefill attention for ONE sequence, over pages
    the chunk's K/V were just written to — the dispatch switch the
    registry routes (`prefill_step` / `prefill_chunk_step` / the PTKS1
    streaming path all land here or on :func:`prefill_impl`):

    q          : [1, C, nh, dh] chunk queries (leading batch of 1 — the
                 step programs' native layout)
    k_pages    : [num_pages, page_size, nh, dh] (one layer)
    page_table : [pages_per_slot] int32 — this sequence's page row
    start      : scalar int32 absolute position of the chunk's first token
    valid      : scalar int32 true token count in this chunk
    returns    : [1, C, nh, dh] in q.dtype — token-identical between arms
                 (rows < valid; parity-tested in interpret mode off-TPU)
    """
    impl = prefill_impl(q.shape[1], page_table.shape[0], k_pages.shape[1],
                        q.shape[2], q.shape[3], q.dtype,
                        quant=k_scale is not None)
    return _prefill_impl_call(impl, q, k_pages, v_pages, page_table, start,
                              valid, k_scale=k_scale, v_scale=v_scale)


def token_page_coords(page_table, pos, active, page_size):
    """(page, offset) for writing token ``pos`` of each of B sequences.

    page_table : [B, pages_per_slot] int32; pos : [B] int32; active : [B]
    bool — inactive slots are routed to TRASH_PAGE, and so is any position
    past the slot's capacity (``pos >= pages_per_slot * page_size``): a
    clamped overflow would silently corrupt the LAST page's KV, which the
    engine then attends over. Returns ([B], [B]).
    """
    maxp = page_table.shape[1]
    idx = pos // page_size
    page = jnp.take_along_axis(page_table,
                               jnp.clip(idx, 0, maxp - 1)[:, None],
                               axis=1)[:, 0]
    page = jnp.where(active & (idx < maxp), page, TRASH_PAGE)
    return page, pos % page_size


def prompt_page_coords(page_table, length, seq_len, page_size):
    """(page, offset) for writing positions 0..seq_len-1 of ONE sequence.

    page_table : [pages_per_slot] int32; length : scalar int32 true prompt
    length (positions >= length — bucket padding — go to TRASH_PAGE, as do
    positions past the slot's capacity rather than corrupting the last
    page). Returns ([seq_len], [seq_len]).
    """
    maxp = page_table.shape[0]
    t = jnp.arange(seq_len)
    idx = t // page_size
    page = jnp.where((t < length) & (idx < maxp),
                     page_table[jnp.clip(idx, 0, maxp - 1)], TRASH_PAGE)
    return page, t % page_size


def chunk_page_coords(page_table, start, valid, seq_len, page_size):
    """(page, offset) for writing a prefill CHUNK — positions
    ``start .. start+seq_len-1`` of ONE sequence.

    page_table : [pages_per_slot] int32; start : scalar int32 absolute
    position of the chunk's first token; valid : scalar int32 true token
    count in this chunk (chunk-padding positions ``i >= valid`` go to
    TRASH_PAGE, as do positions past the slot's capacity). The ``start=0,
    valid=length`` case degenerates to :func:`prompt_page_coords`.
    Returns ([seq_len], [seq_len]).
    """
    maxp = page_table.shape[0]
    t = start + jnp.arange(seq_len)
    idx = t // page_size
    page = jnp.where((jnp.arange(seq_len) < valid) & (idx < maxp),
                     page_table[jnp.clip(idx, 0, maxp - 1)], TRASH_PAGE)
    return page, t % page_size


def verify_page_coords(page_table, pos, valid, page_size):
    """(page, offset) for writing a [B, W] WINDOW of tokens per sequence —
    the speculative-decode verify step's write pattern (`models/gpt.py::
    verify_step`): each slot writes its current token plus up to W-1
    drafted tokens in one step.

    page_table : [B, pages_per_slot] int32; pos : [B, W] int32 absolute
    positions; valid : [B, W] bool — padding drafts, inactive slots, and
    positions past the slot's capacity all route to TRASH_PAGE (rejected
    drafts leave garbage ONLY at positions past the rolled-back length,
    which every later step overwrites before attending). Returns
    ([B, W], [B, W]).
    """
    maxp = page_table.shape[1]
    idx = pos // page_size
    page = jnp.take_along_axis(page_table, jnp.clip(idx, 0, maxp - 1), axis=1)
    page = jnp.where(valid & (idx < maxp), page, TRASH_PAGE)
    return page, pos % page_size


def export_pages(k_pages, v_pages, page_list, k_scales=None, v_scales=None):
    """Gather the listed pages' contents out of the pool — the send side of
    the page-granular KV handoff (a prefill finished on one replica resumes
    decode on another; docs/SERVING.md). The page table makes the transfer a
    page-index gather, never a tensor-relayout.

    Wire integrity lives one layer up (docs/ROBUSTNESS.md "Wire
    integrity"): when these blobs travel as ``PTKV1``/``PTMG1`` bytes,
    `engine.KVHandoff.pack` stamps a blake2b body checksum the unpack
    side verifies BEFORE any page byte is interpreted — a truncated or
    bit-flipped transfer is a typed ``HandoffCorrupt`` refusal, so the
    scatter below only ever sees intact pages. The KV tier store
    (`inference/kv_tiers.py`) rides the same pair of primitives: a
    prefix-page spill is this gather framed as a checksummed ``PTKT1``
    blob per page, and a tier hit re-uploads through `import_pages` —
    pages and scales are immutable once full, so the round trip is
    bit-identical.

    k_pages/v_pages : [num_layers, num_pages, page_size, nh, dh]
    page_list       : [n] int page indices (a sequence's allocation,
                      in token order)
    k_scales/v_scales : optional [num_layers, num_pages, page_size, nh] f32
                      (int8 pools); the listed pages' scales travel with
                      their values so the handoff stays bit-exact
    returns         : (k_blob, v_blob) each [num_layers, n, page_size, nh, dh]
                      — plus (k_s_blob, v_s_blob) when scales were given
    """
    idx = jnp.asarray(page_list, jnp.int32)
    if k_scales is None:
        return k_pages[:, idx], v_pages[:, idx]
    return (k_pages[:, idx], v_pages[:, idx],
            k_scales[:, idx], v_scales[:, idx])


def import_pages(k_pages, v_pages, k_blob, v_blob, page_list,
                 k_scales=None, v_scales=None, k_s_blob=None, v_s_blob=None):
    """Scatter exported page contents into a (different) pool at (different)
    page indices — the receive side of the KV handoff. Only the page IDS
    change across the transfer; contents (and, for int8 pools, their scales)
    land bit-identical, so decode on the importing replica matches decode
    where the prefill ran.

    k_blob/v_blob : [num_layers, n, page_size, nh, dh] from `export_pages`
    page_list     : [n] destination page indices in THIS pool
    returns       : (k_pages, v_pages) updated — plus (k_scales, v_scales)
                    when the scale pools/blobs were given
    """
    idx = jnp.asarray(page_list, jnp.int32)
    kp = k_pages.at[:, idx].set(k_blob.astype(k_pages.dtype))
    vp = v_pages.at[:, idx].set(v_blob.astype(v_pages.dtype))
    if k_scales is None:
        return kp, vp
    return (kp, vp,
            k_scales.at[:, idx].set(jnp.asarray(k_s_blob, k_scales.dtype)),
            v_scales.at[:, idx].set(jnp.asarray(v_s_blob, v_scales.dtype)))


def write_token_kv(k_pages, v_pages, k, v, page_table, pos, active):
    """Scatter one new K/V token per sequence into its page.

    k, v       : [B, nh, dh] — the current token's key/value (one layer)
    page_table : [B, pages_per_slot] int32
    pos        : [B] int32 token position being written
    active     : [B] bool — inactive slots write to TRASH_PAGE
    returns    : (k_pages, v_pages) updated
    """
    page, off = token_page_coords(page_table, pos, active, k_pages.shape[1])
    return k_pages.at[page, off].set(k), v_pages.at[page, off].set(v)


def write_prompt_kv(k_pages, v_pages, k, v, page_table, length):
    """Scatter a whole prompt's K/V (one sequence, one layer) into its pages.

    k, v       : [S, nh, dh] — S is the PADDED bucket length; positions
                 >= length (prompt padding) go to TRASH_PAGE
    page_table : [pages_per_slot] int32
    length     : scalar int32, true prompt length
    returns    : (k_pages, v_pages) updated
    """
    page, off = prompt_page_coords(page_table, length, k.shape[0],
                                   k_pages.shape[1])
    return k_pages.at[page, off].set(k), v_pages.at[page, off].set(v)
