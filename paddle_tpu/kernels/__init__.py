"""Pallas/Mosaic TPU kernels — the hot-op tier (SURVEY.md §7 native component 2,
counterpart of `paddle/fluid/operators/fused/`)."""
