"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

BEYOND the reference (SURVEY §5.7: the snapshot has no sequence parallelism at
all — its long-sequence story is recompute + recompute_hybrid). Two schemes,
both over the 'sp' mesh axis:

- **Ring attention** (`ring_attention`): Q stays put, K/V blocks circulate the
  ring with `jax.lax.ppermute` while each rank accumulates its online-softmax
  partials — attention memory per rank stays O(S/P * S/P) per step and no rank
  ever materializes the full K/V, so max sequence length scales linearly with
  the ring size. Known inefficiency: under causal masking the contiguous
  block-to-rank assignment leaves early ranks computing fully-masked steps
  (~2x causal FLOPs); a zigzag/striped token permutation (balanced early+late
  positions per rank) would fix the imbalance but requires a global reorder of
  the sequence around the attention call — future work.
- **Ulysses** (`ulysses_attention`): `lax.all_to_all` reshards sequence->heads,
  runs dense flash attention on full sequences of H/P heads per rank, and
  reshards back — cheaper collectives when H >= P.

Both are pure-XLA (partial-manual shard_map), composable with dp/mp axes.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from paddle_tpu.framework.jax_compat import shard_map as _shard_map
from jax.sharding import PartitionSpec as P


def _online_update(m, l, acc, logits, vb):
    """One online-softmax accumulation step (f32 stats)."""
    m_c = jnp.max(logits, axis=-1)
    m_new = jnp.maximum(m, m_c)
    # renormalize previous partials; fully-masked rows keep m=-inf safely
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(logits - safe_m[..., None])
    p = jnp.where(jnp.isfinite(logits), p, 0.0)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jax.lax.dot_general(
        p.astype(vb.dtype), vb, (((3,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_attention(q, k, v, causal, scale, mesh, axis="sp"):
    """q,k,v: [B, H, S, D] with S sharded over `axis`. Returns [B, H, S, D]
    with the same sharding. Custom VJP: the backward pass is a SECOND ring that
    recomputes each block's probabilities from the saved logsumexp and
    circulates dK/dV accumulators with the K/V blocks — per-rank residuals are
    O(S/P), never the per-step probability matrices a plain jax.vjp of the
    unrolled loop would save."""
    out, _ = _ring_fwd(q, k, v, causal, scale, mesh, axis)
    return out


def _ring_fwd(q, k, v, causal, scale, mesh, axis):
    n = mesh.shape[axis]
    S = q.shape[2]
    s_local = S // n
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    perm = [(i, (i + 1) % n) for i in range(n)]

    def per_rank(qb, kb, vb):
        r = jax.lax.axis_index(axis)
        B, H, sl, D = qb.shape
        qpos = r * s_local + jnp.arange(sl)
        m = jnp.full((B, H, sl), -jnp.inf, jnp.float32)
        l = jnp.zeros((B, H, sl), jnp.float32)
        acc = jnp.zeros((B, H, sl, D), jnp.float32)
        kc, vc = kb, vb
        for step in range(n):
            blk = (r - step) % n                     # block id currently held
            logits = jax.lax.dot_general(
                qb * s, kc, (((3,), (3,)), ((0, 1), (0, 1))),
                preferred_element_type=jnp.float32)  # [B,H,sl,sl]
            if causal:
                kpos = blk * s_local + jnp.arange(sl)
                mask = kpos[None, :] <= qpos[:, None]
                logits = jnp.where(mask[None, None], logits, -jnp.inf)
            m, l, acc = _online_update(m, l, acc, logits, vc)
            if step < n - 1:
                kc = jax.lax.ppermute(kc, axis, perm)
                vc = jax.lax.ppermute(vc, axis, perm)
        out = acc / jnp.maximum(l, 1e-30)[..., None]
        lse = m + jnp.log(jnp.maximum(l, 1e-30))
        return out.astype(qb.dtype), lse

    spec = P(None, None, axis, None)
    spec3 = P(None, None, axis)
    f = _shard_map(per_rank, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=(spec, spec3), axis_names={axis},
                      check_vma=True)
    out, lse = f(q, k, v)
    return out, (q, k, v, out, lse)


def _ring_bwd(causal, scale, mesh, axis, res, do):
    q, k, v, out, lse = res
    n = mesh.shape[axis]
    S = q.shape[2]
    s_local = S // n
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    perm = [(i, (i + 1) % n) for i in range(n)]

    def per_rank(qb, kb, vb, ob, lseb, dob):
        r = jax.lax.axis_index(axis)
        B, H, sl, D = qb.shape
        qpos = r * s_local + jnp.arange(sl)
        di = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32),
                     axis=-1, keepdims=True)         # [B,H,sl,1]
        dq = jnp.zeros((B, H, sl, D), jnp.float32)
        kc, vc = kb, vb
        dkc = jnp.zeros((B, H, sl, D), jnp.float32)
        dvc = jnp.zeros((B, H, sl, D), jnp.float32)
        for step in range(n):
            blk = (r - step) % n
            logits = jax.lax.dot_general(
                qb * s, kc, (((3,), (3,)), ((0, 1), (0, 1))),
                preferred_element_type=jnp.float32)
            if causal:
                kpos = blk * s_local + jnp.arange(sl)
                mask = kpos[None, :] <= qpos[:, None]
                logits = jnp.where(mask[None, None], logits, -jnp.inf)
            p = jnp.exp(logits - lseb[..., None])    # masked lanes -> 0
            dvc = dvc + jax.lax.dot_general(
                p.astype(dob.dtype), dob, (((2,), (2,)), ((0, 1), (0, 1))),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                dob, vc, (((3,), (3,)), ((0, 1), (0, 1))),
                preferred_element_type=jnp.float32)
            ds = p * (dp - di)                       # [B,H,sl,sl]
            dq = dq + jax.lax.dot_general(
                ds.astype(qb.dtype), kc, (((3,), (2,)), ((0, 1), (0, 1))),
                preferred_element_type=jnp.float32) * s
            dkc = dkc + jax.lax.dot_general(
                ds.astype(qb.dtype), qb, (((2,), (2,)), ((0, 1), (0, 1))),
                preferred_element_type=jnp.float32) * s
            # rotate blocks AND their grad accumulators; after n rotations the
            # accumulated dK/dV are home again
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            dkc = jax.lax.ppermute(dkc, axis, perm)
            dvc = jax.lax.ppermute(dvc, axis, perm)
        return (dq.astype(qb.dtype), dkc.astype(kb.dtype),
                dvc.astype(vb.dtype))

    spec = P(None, None, axis, None)
    spec3 = P(None, None, axis)
    f = _shard_map(
        per_rank, mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec3, spec),
        out_specs=(spec, spec, spec), axis_names={axis}, check_vma=True)
    return f(q, k, v, out, lse, do)


ring_attention.defvjp(_ring_fwd, _ring_bwd)


def ulysses_attention(q, k, v, causal, scale, mesh, axis="sp"):
    """Head<->sequence all-to-all (DeepSpeed-Ulysses scheme): reshard
    [B, H, S/P, D] -> [B, H/P, S, D], dense attention locally, reshard back.
    q,k,v: [B, H, S, D] with S sharded over `axis`; H % axis size == 0."""
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError(
            f"ulysses needs heads ({q.shape[1]}) divisible by sp ({n})")
    from paddle_tpu.kernels.flash_attention import _xla_flash

    def per_rank(qb, kb, vb):
        # local [B, H, sl, D] -> [B, H/n, S, D]
        def seq2head(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        def head2seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        qh, kh, vh = seq2head(qb), seq2head(kb), seq2head(vb)
        out = _xla_flash(qh, kh, vh, causal, scale)
        return head2seq(out)

    spec = P(None, None, axis, None)
    f = _shard_map(per_rank, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec, axis_names={axis}, check_vma=True)
    return f(q, k, v)
