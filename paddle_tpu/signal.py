"""Signal processing — ``paddle.signal`` surface.

Rebuild of the reference's ``python/paddle/signal.py`` (frame :31, overlap_add
:164, stft :249, istft :424; C++ kernels ``paddle/phi/kernels/frame_kernel.h``,
``overlap_add_kernel.h``). Framing is a gather with a statically-computed index
grid — XLA turns it into an efficient strided slice; overlap_add is its
scatter-add transpose, so autograd round-trips exactly.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.core.autograd import apply
from paddle_tpu.ops.common import ensure_tensor
from paddle_tpu import fft as _fft
from paddle_tpu.fft import _apply_or_host

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_np_axis(a, frame_length, hop_length, axis):
    # signal axis is the last (axis=-1) or first (axis=0) per the reference API
    n = a.shape[axis]
    if frame_length > n:
        raise ValueError(
            f"Attribute frame_length should be less equal than sequence length, "
            f"but got ({frame_length}) > ({n})."
        )
    num_frames = 1 + (n - frame_length) // hop_length
    starts = jnp.arange(num_frames) * hop_length
    offsets = jnp.arange(frame_length)
    idx = starts[None, :] + offsets[:, None]          # [frame_length, num_frames]
    if axis in (-1, a.ndim - 1):
        out = jnp.take(a, idx.T, axis=-1)             # [..., num_frames, frame_length]
        return jnp.swapaxes(out, -1, -2)              # [..., frame_length, num_frames]
    elif axis == 0:
        return jnp.take(a, idx, axis=0)               # [frame_length, num_frames, ...]
    raise ValueError(f"Attribute axis should be 0 or -1, got {axis}")


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice a signal into overlapping frames (paddle.signal.frame; ref :31)."""
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length should be positive")
    x = ensure_tensor(x)
    return apply(
        lambda a: _frame_np_axis(a, int(frame_length), int(hop_length), axis),
        x, op_name="frame",
    )


def _overlap_add_axis(a, hop_length, axis):
    if axis in (-1, a.ndim - 1):
        frame_length, num_frames = a.shape[-2], a.shape[-1]
        seq = (num_frames - 1) * hop_length + frame_length
        starts = jnp.arange(num_frames) * hop_length
        idx = starts[None, :] + jnp.arange(frame_length)[:, None]  # [fl, nf]
        out = jnp.zeros(a.shape[:-2] + (seq,), a.dtype)
        return out.at[..., idx].add(a)
    elif axis == 0:
        frame_length, num_frames = a.shape[0], a.shape[1]
        seq = (num_frames - 1) * hop_length + frame_length
        starts = jnp.arange(num_frames) * hop_length
        idx = starts[None, :] + jnp.arange(frame_length)[:, None]
        out = jnp.zeros((seq,) + a.shape[2:], a.dtype)
        return out.at[idx].add(a)
    raise ValueError(f"Attribute axis should be 0 or -1, got {axis}")


def overlap_add(x, hop_length, axis=-1, name=None):
    """Reconstruct a signal from overlapping frames (paddle.signal.overlap_add; ref :164)."""
    if hop_length <= 0:
        raise ValueError("hop_length should be positive")
    x = ensure_tensor(x)
    return apply(lambda a: _overlap_add_axis(a, int(hop_length), axis), x,
                 op_name="overlap_add")


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform (paddle.signal.stft; ref :249).

    x: [..., seq_len] real or complex. Returns [..., n_fft(/2+1), num_frames].
    """
    x = ensure_tensor(x)
    if hop_length is None:
        hop_length = n_fft // 4
    if win_length is None:
        win_length = n_fft
    if window is not None:
        w = ensure_tensor(window).numpy()
        if w.shape != (win_length,):
            raise ValueError(f"window must have shape ({win_length},)")
    else:
        w = np.ones(win_length, np.float32)
    # center-pad the window to n_fft like the reference (:382)
    if win_length < n_fft:
        pad_l = (n_fft - win_length) // 2
        w = np.pad(w, (pad_l, n_fft - win_length - pad_l))
    w = jnp.asarray(w)
    is_complex = np.issubdtype(np.dtype(x.dtype), np.complexfloating)
    if is_complex and onesided:
        raise ValueError("onesided is not supported for complex input")

    def _stft(a):
        if center:
            pad = n_fft // 2
            widths = [(0, 0)] * (a.ndim - 1) + [(pad, pad)]
            a = jnp.pad(a, widths, mode=pad_mode)
        frames = _frame_np_axis(a, n_fft, hop_length, -1)   # [..., n_fft, nf]
        frames = frames * w[:, None]
        if onesided and not is_complex:
            spec = jnp.fft.rfft(frames, axis=-2)
        else:
            spec = jnp.fft.fft(frames, axis=-2)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(float(n_fft), spec.real.dtype))
        return spec

    return _apply_or_host(_stft, x, op_name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT with overlap-add + window-envelope normalization
    (paddle.signal.istft; ref :424)."""
    x = ensure_tensor(x)
    if hop_length is None:
        hop_length = n_fft // 4
    if win_length is None:
        win_length = n_fft
    if window is not None:
        w = ensure_tensor(window).numpy().astype(np.float32)
    else:
        w = np.ones(win_length, np.float32)
    if win_length < n_fft:
        pad_l = (n_fft - win_length) // 2
        w = np.pad(w, (pad_l, n_fft - win_length - pad_l))
    w = jnp.asarray(w)

    def _istft(spec):
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(float(n_fft), spec.real.dtype))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-2)
        else:
            frames = jnp.fft.ifft(spec, axis=-2)
            if not return_complex:
                frames = frames.real
        frames = frames * w[:, None]
        sig = _overlap_add_axis(frames, hop_length, -1)
        env = _overlap_add_axis(
            jnp.broadcast_to((w * w)[:, None], frames.shape[-2:]), hop_length, -1)
        sig = sig / jnp.where(env > 1e-11, env, 1.0)
        if center:
            pad = n_fft // 2
            sig = sig[..., pad:sig.shape[-1] - pad]
        if length is not None:
            sig = sig[..., :length]
        return sig

    return _apply_or_host(_istft, x, op_name="istft")
