"""incubate.nn fused layers (ref: `python/paddle/incubate/nn/` —
FusedMultiHeadAttention, FusedFeedForward, FusedMultiTransformer).

On TPU "fused" means: one traced region XLA/Pallas fuses — attention goes through
the flash-attention kernel, the MLP is a single jit region.
"""
import functools

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn.layers.transformer import MultiHeadAttention
from paddle_tpu.nn.layers.common import Linear, Dropout
from paddle_tpu.nn.layers.norm import LayerNorm
from paddle_tpu.nn import functional as F


class FusedMultiHeadAttention(Layer):
    """ref `incubate/nn/layer/fused_transformer.py` FusedMultiHeadAttention."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None, normalize_before=False,
                 need_weights=False, qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.attn = MultiHeadAttention(embed_dim, num_heads,
                                       dropout=attn_dropout_rate)
        self.ln = LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        residual = query
        x = self.ln(query) if self.normalize_before else query
        out = self.attn(x, key, value, attn_mask, cache)
        out = residual + self.dropout(out if not isinstance(out, tuple)
                                      else out[0])
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.ln = LayerNorm(d_model, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)
        self.act_dropout = Dropout(act_dropout_rate if act_dropout_rate
                                   is not None else dropout_rate)
        self.activation = getattr(F, activation)

    def forward(self, src):
        residual = src
        x = self.ln(src) if self.normalize_before else src
        x = self.linear2(self.act_dropout(self.activation(self.linear1(x))))
        out = residual + self.dropout(x)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate,
            attn_dropout_rate if attn_dropout_rate is not None else dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask, cache=cache)
        return self.ffn(out)


class FusedLayerNorm(Layer):
    """LayerNorm over the authored Pallas kernel
    (`paddle_tpu/kernels/pallas/fused_layernorm.py` — the counterpart of the
    reference's fused_layernorm CUDA kernels). Single pass per row for the
    forward; analytic one-pass backward with in-kernel dgamma/dbeta partials."""

    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        import numpy as _np
        from paddle_tpu.core.tensor import Parameter
        from paddle_tpu.kernels import registry
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        if len(normalized_shape) != 1:
            raise ValueError("FusedLayerNorm fuses over the last axis only")
        d = int(normalized_shape[0])
        self.epsilon = epsilon
        self.weight = Parameter(_np.ones(d, _np.float32))
        self.bias = Parameter(_np.zeros(d, _np.float32))
        # registry-routed (kernels/registry.py): one pallas impl today —
        # interpret mode off-TPU inside the kernel. Resolved ONCE at
        # layer construction (forward runs EAGERLY per call — a
        # per-forward dispatch would count thousands of times per step
        # and drown the 'which kernel serves traffic' snapshot); a
        # future xla candidate lands as a registry drop-in here
        self._ln_impl = registry.dispatch("fused_layernorm")

    def forward(self, x):
        from paddle_tpu.core.autograd import apply
        from paddle_tpu.kernels.pallas import fused_layer_norm
        from paddle_tpu.ops.common import ensure_tensor
        x = ensure_tensor(x)
        return apply(
            lambda a, g, b: fused_layer_norm(a, g, b, eps=self.epsilon),
            x, self.weight, self.bias, op_name="fused_layer_norm")


@functools.lru_cache(maxsize=1)
def _rope_impl() -> str:
    """Resolve (and count) the rope impl ONCE per process — the
    functional runs eagerly per call, so an uncached dispatch would
    count per invocation instead of per selection."""
    from paddle_tpu.kernels import registry
    return registry.dispatch("fused_rope")


def fused_rotary_position_embedding(q, k, cos, sin, name=None):
    """Fused rope over the authored Pallas kernel
    (`paddle_tpu/kernels/pallas/rotary.py`; ref newer-branch `fused_rope`).
    q/k: [B, H, S, D] tensors; cos/sin: [S, D/2]."""
    from paddle_tpu.core.autograd import apply
    from paddle_tpu.kernels.pallas import apply_rotary_emb
    from paddle_tpu.ops.common import ensure_tensor
    _rope_impl()
    q, k = ensure_tensor(q), ensure_tensor(k)
    cos, sin = ensure_tensor(cos), ensure_tensor(sin)
    return apply(lambda a, b, c, s: apply_rotary_emb(a, b, c, s),
                 q, k, cos, sin, op_name="fused_rope", n_outputs=2)


class FusedMultiTransformer(Layer):
    """N pre-LN decoder layers in one traced region
    (ref `incubate/nn/layer/fused_transformer.py` FusedMultiTransformer — the
    reference fuses all layers into one CUDA op, `fused_multi_transformer_op.cu`;
    here the whole stack is one jit region XLA fuses, with attention on the
    flash kernel)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, num_layers=1,
                 epsilon=1e-5, **kwargs):
        super().__init__()
        if not normalize_before:
            raise NotImplementedError(
                "FusedMultiTransformer is pre-LN only (ref constraint)")
        self.layers = []
        for i in range(num_layers):
            blk = FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward, dropout_rate,
                activation=activation, normalize_before=True)
            self.add_sublayer(f"layer_{i}", blk)
            self.layers.append(blk)

    def forward(self, src, attn_mask=None, caches=None, **kwargs):
        out = src
        new_caches = [] if caches is not None else None
        for i, blk in enumerate(self.layers):
            cache = caches[i] if caches is not None else None
            out = blk(out, src_mask=attn_mask, cache=cache)
            if isinstance(out, tuple):
                out, c = out
                if new_caches is not None:
                    new_caches.append(c)
        return (out, new_caches) if caches is not None else out
