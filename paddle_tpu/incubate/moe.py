"""Mixture-of-Experts with expert parallelism over the 'ep' mesh axis.

Counterpart of the reference MoE stack — `MoELayer`
(`python/paddle/incubate/distributed/models/moe/moe_layer.py:260`), gates
(`moe/gate/{naive,gshard,switch}_gate.py`) and the `global_scatter` /
`global_gather` all-to-all dispatch ops
(`paddle/fluid/operators/collective/global_scatter_op.cc:80`) — redesigned
GShard-style for XLA:

- routing produces STATIC-shape dispatch/combine tensors via capacity padding
  (SURVEY §7 hard-part #5: no dynamic shapes on TPU); overflow tokens drop,
  exactly like the reference's capacity mechanism;
- token -> expert movement is an einsum against the dispatch mask with 'ep'
  sharding constraints — GSPMD lowers the resharding to the all-to-all the
  reference codes as global_scatter/global_gather;
- expert FFNs run as ONE vmapped computation over weights stacked on a leading
  [E] axis sharded over 'ep' (each ep rank holds E/ep experts);
- the load-balance auxiliary loss (`gshard_gate.py`) is returned through
  `MoELayer.l_aux` and participates in autograd.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.tensor import Tensor, Parameter
from paddle_tpu.nn.layer import Layer
from paddle_tpu.framework.param_attr import ParamAttr
from paddle_tpu.nn import initializer as I
from paddle_tpu.distributed.mesh import get_mesh


def _capacity(n_tokens, n_experts, top_k, factor):
    return max(int(math.ceil(top_k * n_tokens / n_experts * factor)), 4)


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def _top1_indices(probs, capacity):
    """Switch routing (ref `switch_gate.py`): top-1 with capacity. Index
    form: (expert_idx [N,1], pos [N,1], gate [N,1], kept [N,1], aux)."""
    n, e = probs.shape
    idx = jnp.argmax(probs, axis=-1)                       # [N]
    mask = _one_hot(idx, e)                                # [N, E]
    # position of each token inside its expert's buffer
    pos_d = jnp.cumsum(mask, axis=0) * mask - mask         # [N, E] 0-based
    keep = (pos_d < capacity) * mask                       # overflow drops
    pos = jnp.sum(pos_d * keep, axis=-1).astype(jnp.int32)  # [N]
    kept = jnp.sum(keep, axis=-1)                          # [N] 0/1
    gate = jnp.sum(probs * keep, axis=-1)                  # selected prob
    # switch aux loss: E * sum_e (fraction_tokens_e * mean_prob_e)
    frac = jnp.mean(mask, axis=0)
    mean_p = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_p)
    return (idx[:, None].astype(jnp.int32), pos[:, None], gate[:, None],
            kept[:, None], aux)


def _top2_indices(probs, capacity):
    """GShard top-2 routing (ref `gshard_gate.py`) in index form."""
    n, e = probs.shape
    idx1 = jnp.argmax(probs, axis=-1)
    mask1 = _one_hot(idx1, e)
    probs2 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs2, axis=-1)
    mask2 = _one_hot(idx2, e)

    pos1_d = jnp.cumsum(mask1, axis=0) * mask1 - mask1
    keep1 = (pos1_d < capacity) * mask1
    # expert buffers already hold count1 tokens when the 2nd choices land
    count1 = jnp.sum(mask1, axis=0, keepdims=True)
    pos2_d = (jnp.cumsum(mask2, axis=0) * mask2 - mask2) + count1 * mask2
    keep2 = (pos2_d < capacity) * mask2

    g1 = jnp.sum(probs * keep1, axis=-1)
    g2 = jnp.sum(probs * keep2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    p1 = jnp.sum(pos1_d * keep1, axis=-1).astype(jnp.int32)
    p2 = jnp.sum(pos2_d * keep2, axis=-1).astype(jnp.int32)
    frac = jnp.mean(mask1, axis=0)
    mean_p = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_p)
    idx = jnp.stack([idx1, idx2], axis=1).astype(jnp.int32)   # [N, 2]
    pos = jnp.stack([p1, p2], axis=1)
    gate = jnp.stack([g1, g2], axis=1)
    kept = jnp.stack([jnp.sum(keep1, axis=-1),
                      jnp.sum(keep2, axis=-1)], axis=1)
    return idx, pos, gate, kept, aux


def _naive_topk_indices(probs, capacity, k):
    """True naive top-k (ref `moe/gate/naive_gate.py`): top-k by value, gate
    values UNNORMALIZED (the reference returns raw softmax scores — no
    GShard renorm), capacity only as the static-shape bound."""
    n, e = probs.shape
    vals, idx = jax.lax.top_k(probs, k)                    # [N, K]
    # buffer positions: count earlier (token, choice) pairs per expert over
    # the token-major flattening — matches the sequential-argmax order
    flat_mask = _one_hot(idx.reshape(-1), e)               # [N*K, E]
    pos_d = jnp.cumsum(flat_mask, axis=0) * flat_mask - flat_mask
    keep = (pos_d < capacity) * flat_mask
    pos = jnp.sum(pos_d * keep, axis=-1).astype(jnp.int32).reshape(n, k)
    kept = jnp.sum(keep, axis=-1).reshape(n, k)
    frac = jnp.mean(flat_mask.reshape(n, k, e)[:, 0], axis=0)
    mean_p = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_p)
    return idx.astype(jnp.int32), pos, vals, kept, aux


def _dense_from_indices(idx, pos, gate, kept, e, capacity):
    """Index form -> dense GShard dispatch/combine [N, E, C] (the einsum
    fallback path; also the back-compat Gate.routing return value)."""
    d_k = (kept[..., None, None]
           * _one_hot(idx, e)[..., None]
           * _one_hot(pos, capacity)[..., None, :])        # [N, K, E, C]
    dispatch = jnp.minimum(jnp.sum(d_k, axis=1), 1.0)
    combine = jnp.sum(d_k * gate[..., None, None], axis=1)
    return dispatch, combine


def _scatter_dispatch(flat, idx, pos, kept, e, capacity):
    """Token -> expert-buffer movement WITHOUT the [N,E,C] one-hot tensor:
    each (token, choice) writes into slot expert*C + pos via scatter-add —
    O(N*K*D) data movement, the static-shape analog of the reference's
    `global_scatter` (`global_scatter_op.cc:80`), vs the einsum's
    O(N*E*C*D) FLOPs. Slots are unique per (expert, pos) by construction,
    and dropped pairs target a sentinel row that is sliced off."""
    n, k = idx.shape
    d = flat.shape[-1]
    slot = idx * capacity + pos                            # [N, K]
    slot = jnp.where(kept > 0, slot, e * capacity)         # sentinel
    buf = jnp.zeros((e * capacity + 1, d), flat.dtype)
    src = jnp.broadcast_to(flat[:, None, :], (n, k, d)).reshape(n * k, d)
    buf = buf.at[slot.reshape(-1)].add(src)
    return buf[:-1].reshape(e, capacity, d)


def _gather_combine(exp_out, idx, pos, gate, kept, capacity):
    """Expert buffers -> tokens (the `global_gather` analog): gather each
    (token, choice)'s slot and mix by gate weight."""
    e = exp_out.shape[0]
    d = exp_out.shape[-1]
    flat_out = exp_out.reshape(e * capacity, d)
    slot = jnp.clip(idx * capacity + pos, 0, e * capacity - 1)
    vals = flat_out[slot.reshape(-1)].reshape(idx.shape + (d,))  # [N, K, D]
    w = (gate * (kept > 0)).astype(vals.dtype)
    return jnp.sum(vals * w[..., None], axis=1)            # [N, D]


class BaseGate(Layer):
    top_k = 1

    def __init__(self, d_model, num_experts, capacity_factor=2.0,
                 weight_attr=None):
        super().__init__()
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.weight = self.create_parameter(
            [d_model, num_experts], attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Normal(0.0, 0.02))

    def routing_indices(self, probs, capacity):
        """(expert_idx [N,K], pos [N,K], gate [N,K], kept [N,K], aux)."""
        raise NotImplementedError

    def effective_capacity(self, n_tokens, capacity):
        """Static per-expert buffer size the layer must allocate."""
        return capacity

    def routing(self, probs, capacity):
        """Dense GShard (dispatch [N,E,C], combine [N,E,C], aux) — derived
        from the index form; kept for the einsum path and back-compat."""
        idx, pos, gate, kept, aux = self.routing_indices(probs, capacity)
        e = self.num_experts
        dispatch, combine = _dense_from_indices(idx, pos, gate, kept, e,
                                                capacity)
        return dispatch, combine, aux


class SwitchGate(BaseGate):
    """ref `moe/gate/switch_gate.py` — top-1 capacity routing."""
    top_k = 1

    def routing_indices(self, probs, capacity):
        return _top1_indices(probs, capacity)


class GShardGate(BaseGate):
    """ref `moe/gate/gshard_gate.py` — top-2 capacity routing with
    normalized gate weights."""
    top_k = 2

    def routing_indices(self, probs, capacity):
        return _top2_indices(probs, capacity)


class NaiveGate(BaseGate):
    """ref `moe/gate/naive_gate.py` — true naive top-k: raw (unnormalized)
    softmax scores as gate weights, NO GShard renorm. The reference drops
    nothing (dynamic counts over brpc); static TPU shapes need a capacity
    bound, so the default capacity_factor is sized to make drops impossible
    for the worst case only when ``no_drop=True`` (capacity = N), else the
    generous 4.0 bound applies."""
    top_k = 2

    def __init__(self, d_model, num_experts, capacity_factor=4.0,
                 weight_attr=None, top_k=2, no_drop=False):
        super().__init__(d_model, num_experts, capacity_factor, weight_attr)
        self.top_k = int(top_k)
        self.no_drop = bool(no_drop)

    def effective_capacity(self, n_tokens, capacity):
        # top_k returns DISTINCT experts per token, so one expert receives at
        # most n_tokens (token, choice) pairs — that is the no-drop bound
        return n_tokens if self.no_drop else capacity

    def routing_indices(self, probs, capacity):
        return _naive_topk_indices(probs, capacity, self.top_k)


class MoELayer(Layer):
    """ref `moe_layer.py:260`. ``experts``: list of structurally identical
    Layers (one per expert; each maps [*, d_model] -> [*, d_model]). Their
    params are stacked on a leading [E] axis sharded over 'ep'; the dense
    compute runs once under vmap. Aux load-balance loss lands in ``l_aux``
    (add it to the training loss, ref moe aux_loss convention)."""

    def __init__(self, d_model=None, experts=None, gate=None,
                 capacity_factor=None, moe_group=None, mp_group=None, **kw):
        super().__init__()
        if not experts:
            raise ValueError("MoELayer needs a non-empty expert list")
        self.num_experts = len(experts)
        if gate is None:
            gate = GShardGate(d_model, self.num_experts)
        elif isinstance(gate, str):
            cls = {"naive": NaiveGate, "gshard": GShardGate,
                   "switch": SwitchGate}[gate]
            gate = cls(d_model, self.num_experts)
        self.gate = gate
        if capacity_factor is not None:
            self.gate.capacity_factor = capacity_factor
        # stack expert params over [E]; experts themselves stay unregistered
        # (template-execution pattern, same as the SPMD pipeline engine) —
        # bypass Layer.__setattr__ so expert 0 isn't registered as a sublayer
        object.__setattr__(self, "_template", experts[0])
        object.__setattr__(self, "_template_params",
                           list(experts[0].parameters()))
        trees = [[p._data for p in ex.parameters()] for ex in experts]
        ref0 = trees[0]
        for i, tree in enumerate(trees[1:], 1):
            if len(tree) != len(ref0) or any(
                    a.shape != b.shape or a.dtype != b.dtype
                    for a, b in zip(tree, ref0)):
                raise ValueError(f"expert {i} differs structurally from "
                                 "expert 0 — experts must be uniform")
        mesh = get_mesh()
        self._stacked = []
        for i in range(len(ref0)):
            arr = jnp.stack([t[i] for t in trees])
            if mesh is not None and "ep" in mesh.axis_names \
                    and self.num_experts % mesh.shape["ep"] == 0:
                arr = jax.device_put(arr, NamedSharding(
                    mesh, P("ep", *([None] * (arr.ndim - 1)))))
            prm = Parameter(arr)
            prm.name = f"moe_expert_param_{i}"
            prm.is_expert = True      # consumed by ClipGradForMOEByGlobalNorm
            self.add_parameter(f"moe_expert_param_{i}", prm)
            self._stacked.append(prm)
        self.l_aux = None

    def forward(self, x):
        from paddle_tpu.core.autograd import apply
        from paddle_tpu.ops.common import ensure_tensor
        x = ensure_tensor(x)
        orig_shape = tuple(x.shape)
        d_model = orig_shape[-1]
        n_tokens = int(np.prod(orig_shape[:-1]))
        e = self.num_experts
        cap = self.gate.effective_capacity(
            n_tokens, _capacity(n_tokens, e, self.gate.top_k,
                                self.gate.capacity_factor))
        mesh = get_mesh()
        ep_ok = (mesh is not None and "ep" in mesh.axis_names
                 and e % mesh.shape["ep"] == 0 and mesh.shape["ep"] > 1)
        tpl_params = self._template_params
        template = self._template
        template.train() if self.training else template.eval()
        routing_indices = self.gate.routing_indices
        # a pre-round-3 custom gate may override only the dense routing()
        # contract: honor it through the einsum path
        legacy_dense = (
            type(self.gate).routing_indices is BaseGate.routing_indices
            and type(self.gate).routing is not BaseGate.routing)
        from paddle_tpu.framework.flags import flag_value
        mode = flag_value("moe_dispatch")
        # einsum pays O(N*E*C*D) FLOPs for what is data MOVEMENT; scatter
        # moves O(N*K*D). Keep einsum only where the one-hot tensor is tiny
        # (XLA fuses it well there and the scatter has fixed overheads).
        use_scatter = not legacy_dense and (mode == "scatter" or (
            mode == "auto" and n_tokens * e * cap * d_model > (1 << 22)))
        legacy_routing = self.gate.routing if legacy_dense else None

        def prim(gw, xa, *stacked):
            flat = xa.reshape(n_tokens, d_model)
            logits = jnp.dot(flat.astype(jnp.float32),
                             gw.astype(jnp.float32))
            probs = jax.nn.softmax(logits, axis=-1)         # [N, E]
            if use_scatter:
                idx, pos, gate_w, kept, aux = routing_indices(probs, cap)
                # sort-free index dispatch (the global_scatter analog)
                exp_in = _scatter_dispatch(flat, idx, pos, kept, e, cap)
            else:
                if legacy_dense:
                    dispatch, combine, aux = legacy_routing(probs, cap)
                else:
                    idx, pos, gate_w, kept, aux = routing_indices(probs, cap)
                    dispatch, combine = _dense_from_indices(
                        idx, pos, gate_w, kept, e, cap)
                # token -> expert buffers; GSPMD turns the 'ep' resharding
                # into the global_scatter all-to-all
                exp_in = jnp.einsum("nec,nd->ecd",
                                    dispatch.astype(flat.dtype), flat)
            if ep_ok:
                exp_in = jax.lax.with_sharding_constraint(
                    exp_in, NamedSharding(mesh, P("ep", None, None)))

            def expert_fn(params, inp):
                from paddle_tpu.distributed.fleet.pipeline import (
                    template_rng_guard)
                saved = [(t._data, t._grad_node, t._out_slot)
                         for t in tpl_params]
                for t, a in zip(tpl_params, params):
                    t._data = a
                    t._grad_node = None
                try:
                    with template_rng_guard("the MoE expert body"):
                        return template(Tensor(inp, _internal=True))._data
                finally:
                    for t, (d, nd, sl) in zip(tpl_params, saved):
                        t._data = d
                        t._grad_node = nd
                        t._out_slot = sl

            exp_out = jax.vmap(expert_fn)(list(stacked), exp_in)  # [E, C, D]
            if ep_ok:
                exp_out = jax.lax.with_sharding_constraint(
                    exp_out, NamedSharding(mesh, P("ep", None, None)))
            if use_scatter:
                out = _gather_combine(exp_out.astype(jnp.float32), idx, pos,
                                      gate_w, kept, cap).astype(xa.dtype)
            else:
                out = jnp.einsum("ecd,nec->nd", exp_out.astype(jnp.float32),
                                 combine).astype(xa.dtype)
            return out.reshape(orig_shape), aux

        out, aux = apply(prim, self.gate.weight, x, *self._stacked,
                         op_name="moe_layer", n_outputs=2)
        self.l_aux = aux
        return out
