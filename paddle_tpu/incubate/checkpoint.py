"""Auto-checkpoint: periodic training-state snapshots + epoch resume.

Counterpart of the reference's
`fluid/incubate/checkpoint/auto_checkpoint.py` — `train_epoch_range` (:72)
wraps the user's epoch loop, snapshots training state every
``save_checkpoint_inter`` epochs (:642 keys snapshots by job id), and on
relaunch (the launcher restarts a failed pod — `fleet/elastic`) resumes from
the recorded epoch instead of epoch 0.

TPU-native shape: state is the models'/optimizers' state_dicts saved through
the sharded checkpoint codec (`distributed/checkpoint.py` — global arrays,
mesh-independent), so a restarted job may resume under a different parallel
plan. Activation:

- pass ``checkpoint_dir=...`` explicitly, or
- set ``PADDLE_AUTO_CHECKPOINT_DIR`` (the launcher analog of the reference's
  PADDLE_RUNNING_ENV/PADDLE_JOB_ID gating); without either the range
  degrades to a plain ``range()`` exactly like the reference outside a
  managed environment.
"""
from __future__ import annotations

import json
import os
import shutil

__all__ = ["train_epoch_range"]


def _load_into(obj, path):
    from paddle_tpu.distributed.checkpoint import load_sharded
    obj.set_state_dict(load_sharded(path))


def _save(obj, path):
    from paddle_tpu.distributed.checkpoint import save_sharded
    save_sharded(obj.state_dict(), path)


def train_epoch_range(max_epoch_num, save_checkpoint_inter=1, models=(),
                      optimizers=(), checkpoint_dir=None, keep_max=2,
                      name="acp"):
    """Epoch generator with crash-resume semantics (ref
    `auto_checkpoint.py:72`).

    Usage::

        for epoch in train_epoch_range(10, models=[m], optimizers=[opt],
                                       checkpoint_dir="ckpt/job0"):
            ...train one epoch...

    After a restart with the same ``checkpoint_dir`` the loop continues
    from the epoch following the last completed snapshot, with model and
    optimizer state restored. Snapshots are written ATOMICALLY: the epoch
    marker (``acp_meta.json``) is only updated after the state directories
    are fully on disk, so a crash mid-save resumes from the previous good
    snapshot."""
    d = checkpoint_dir or os.environ.get("PADDLE_AUTO_CHECKPOINT_DIR")
    models = list(models)
    optimizers = list(optimizers)
    if d is None:
        yield from range(max_epoch_num)
        return
    os.makedirs(d, exist_ok=True)
    meta_path = os.path.join(d, f"{name}_meta.json")
    start = 0
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        last = meta["epoch"]
        snap = os.path.join(d, f"epoch_{last}")
        for i, m in enumerate(models):
            _load_into(m, os.path.join(snap, f"model_{i}"))
        for i, o in enumerate(optimizers):
            _load_into(o, os.path.join(snap, f"optimizer_{i}"))
        start = last + 1

    def snapshot(epoch):
        snap = os.path.join(d, f"epoch_{epoch}")
        tmp = snap + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        for i, m in enumerate(models):
            _save(m, os.path.join(tmp, f"model_{i}"))
        for i, o in enumerate(optimizers):
            _save(o, os.path.join(tmp, f"optimizer_{i}"))
        if os.path.exists(snap):
            # crash after rename but before the meta write: replace the
            # orphaned snapshot
            shutil.rmtree(snap)
        os.replace(tmp, snap)
        with open(meta_path + ".tmp", "w") as f:
            json.dump({"epoch": epoch, "max_epoch_num": max_epoch_num}, f)
        os.replace(meta_path + ".tmp", meta_path)
        # prune old snapshots beyond keep_max
        snaps = sorted(
            (e for e in os.listdir(d) if e.startswith("epoch_")
             and not e.endswith(".tmp")),
            key=lambda s: int(s.split("_")[1]))
        for old in snaps[:-keep_max]:
            shutil.rmtree(os.path.join(d, old), ignore_errors=True)

    for epoch in range(start, max_epoch_num):
        yield epoch
        if ((epoch + 1 - start) % save_checkpoint_inter == 0
                or epoch == max_epoch_num - 1):
            snapshot(epoch)
