"""paddle.incubate (ref: `python/paddle/incubate/`) — fused transformer APIs, MoE,
autograd prims. Fused ops route to the Pallas kernels / XLA fusions."""
from paddle_tpu.incubate import nn  # noqa: F401
from paddle_tpu.incubate import distributed  # noqa: F401
from paddle_tpu.incubate import moe  # noqa: F401


def softmax_mask_fuse_upper_triangle(x):
    """Fused causal-masked softmax (ref `incubate/operators/`)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.autograd import apply
    from paddle_tpu.ops.common import ensure_tensor
    x = ensure_tensor(x)

    def prim(a):
        q, k = a.shape[-2], a.shape[-1]
        mask = jnp.tril(jnp.ones((q, k), bool), k=k - q)
        return jax.nn.softmax(jnp.where(mask, a, -1e30), axis=-1)

    return apply(prim, x, op_name="softmax_mask_fuse_upper_triangle")
from paddle_tpu.incubate import asp  # noqa: F401
from paddle_tpu.incubate import autograd  # noqa: F401
from paddle_tpu.incubate import optimizer  # noqa: F401
from paddle_tpu.incubate.optimizer import (  # noqa: F401
    DistributedFusedLamb, LookAhead, ModelAverage)
