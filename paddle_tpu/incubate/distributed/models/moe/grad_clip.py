"""MoE-aware global-norm gradient clipping.

Counterpart of the reference's `ClipGradForMOEByGlobalNorm`
(`python/paddle/incubate/distributed/models/moe/grad_clip.py:22`): the global
norm combines a regular-parameter term with an expert-parameter term —
``global_norm = sqrt(||g_regular||^2 + ||g_expert||^2)`` — where the
reference all-reduces the expert term across the moe group first (each of its
ranks holds DIFFERENT experts, so a naive global norm would miss the others'
expert grads).

On the TPU mesh the stacked expert parameters are GLOBAL arrays sharded over
'ep' (`incubate/moe.py` stacks experts on a leading [E] axis), so their
gradients already aggregate the whole expert population and the combined norm
is exact without a hand-coded allreduce. In eager multi-process mode
(`init_parallel_env`), pass ``moe_group`` and the expert term is summed over
the group via the collective facade — the reference's semantics verbatim.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.clip import ClipGradBase

__all__ = ["ClipGradForMOEByGlobalNorm"]


class ClipGradForMOEByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, is_expert_param_func=None, moe_group=None,
                 group_name="default_moe_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name
        self.moe_group = moe_group
        self._is_expert = (is_expert_param_func or
                           (lambda p: getattr(p, "is_expert", False)))

    @staticmethod
    def _sq_sum(grads):
        if not grads:
            return jnp.zeros((), jnp.float32)
        return sum(jnp.sum(g._data.astype(jnp.float32) ** 2) for g in grads)

    def _dygraph_clip(self, params_grads):
        regular, expert = [], []
        for p, g in params_grads:
            if g is None or getattr(p, "need_clip", True) is False:
                continue
            (expert if self._is_expert(p) else regular).append(g)
        if not regular and not expert:
            return params_grads
        sq_reg = self._sq_sum(regular)
        sq_exp = self._sq_sum(expert)
        if expert and self.moe_group is not None and \
                getattr(self.moe_group, "nranks", 1) > 1:
            # eager multi-process: each moe rank holds different experts
            import paddle_tpu.distributed as dist
            t = Tensor(sq_exp, _internal=True)
            dist.all_reduce(t, group=self.moe_group)
            sq_exp = t._data
        global_norm = jnp.sqrt(sq_reg + sq_exp)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or getattr(p, "need_clip", True) is False:
                out.append((p, g))
            else:
                out.append((p, Tensor((g._data * scale).astype(g.dtype),
                                      _internal=True)))
        return out
