"""paddle.incubate.distributed.models.moe (ref moe_layer.py / gate/*.py)."""
from paddle_tpu.incubate.moe import (  # noqa: F401
    MoELayer, BaseGate, NaiveGate, GShardGate, SwitchGate)
from paddle_tpu.incubate.distributed.models.moe.grad_clip import (  # noqa: F401
    ClipGradForMOEByGlobalNorm)
