"""incubate optimizers: LookAhead, ModelAverage
(ref `python/paddle/incubate/optimizer/lookahead.py` :30,
`modelaverage.py` :31).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = ["LookAhead", "ModelAverage", "DistributedFusedLamb"]


from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.fleet.meta_optimizers import _MetaOptimizerBase
from paddle_tpu.optimizer.optimizer import Optimizer


class LookAhead(_MetaOptimizerBase):
    """k-step lookahead: slow weights pulled toward the fast optimizer's
    weights every k steps (Zhang et al.; ref lookahead.py:30). Delegation /
    minimize ride the shared meta-optimizer base."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        if int(k) < 1:
            raise ValueError("k should be >= 1")
        super().__init__(inner_optimizer)
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_num = 0
        self._slow = {}

    @property
    def inner_optimizer(self):
        return self._inner_opt

    def step(self):
        params = self._inner_opt._parameter_list
        if self._step_num == 0:
            for i, p in enumerate(params):
                self._slow[i] = p._data
        self._inner_opt.step()
        self._step_num += 1
        if self._step_num % self.k == 0:
            for i, p in enumerate(params):
                slow = self._slow[i] + self.alpha * (p._data - self._slow[i])
                self._slow[i] = slow
                p._write(slow.astype(p._data.dtype))

    def state_dict(self):
        sd = self._inner_opt.state_dict()
        sd["@LookAhead.slow"] = {i: np.asarray(v)
                                 for i, v in self._slow.items()}
        sd["@LookAhead.step"] = self._step_num
        return sd

    def set_state_dict(self, state):
        state = dict(state)   # don't mutate the caller's dict
        slow = state.pop("@LookAhead.slow", None)
        self._step_num = state.pop("@LookAhead.step", 0)
        if slow is not None:
            self._slow = {i: jnp.asarray(v) for i, v in slow.items()}
        self._inner_opt.set_state_dict(state)


class ModelAverage:
    """Maintains a running average of parameters for evaluation
    (ref modelaverage.py:31): `apply()` swaps averaged weights in,
    `restore()` swaps training weights back."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError("parameters is required")
        self._params = list(parameters)
        self.rate = float(average_window_rate)
        self.min_window = int(min_average_window)
        self.max_window = int(max_average_window)
        self._sum = [jnp.zeros_like(p._data) for p in self._params]
        self._count = 0
        self._backup = None

    def step(self):
        """Accumulate the current weights (call after each optimizer.step)."""
        if self._count >= self.max_window:
            # restart the window like the reference's sum rotation
            shrink = max(self.min_window, int(self.rate * self._count))
            scale = shrink / max(self._count, 1)
            self._sum = [s * scale for s in self._sum]
            self._count = shrink
        self._sum = [s + p._data for s, p in zip(self._sum, self._params)]
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        """Swap averaged weights in (context-manager friendly)."""
        if self._count == 0:
            return self
        if self._backup is None:   # double-apply must not clobber the backup
            self._backup = [p._data for p in self._params]
        for p, s in zip(self._params, self._sum):
            p._write((s / self._count).astype(p._data.dtype))
        if not need_restore:
            self._backup = None
        return self

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p, b in zip(self._params, self._backup):
            p._write(b)
        self._backup = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.restore()


class DistributedFusedLamb(Optimizer):
    """LAMB for large-batch distributed training (ref
    `python/paddle/incubate/optimizer/distributed_fused_lamb.py:82`).

    The reference fuses all params into flat aligned buffers, shards the
    optimizer states across ranks, all-reduces flat grads, clips by a
    global grad norm, and runs one fused CUDA kernel
    (`distributed_fused_lamb_op.cu`). TPU-native collapse: grads are already
    globally averaged in-graph by GSPMD (the 'allreduce' is derived from
    shardings, so ``clip_after_allreduce`` is ALWAYS effectively True —
    recorded for API parity), state sharding is jax.sharding placement on
    the moment accumulators (compose further with
    `distributed.sharding.shard_optimizer_states` / host offload), and the
    whole update lives inside the one captured step program. What remains
    semantically is implemented exactly: the LAMB trust-ratio update,
    built-in global-norm clipping (``max_global_grad_norm``),
    ``exclude_from_weight_decay_fn``, and internal gradient accumulation
    (``gradient_accumulation_steps``: parameter update fires every k-th
    step() with the MEAN of the k grads — the reference's acc_step /
    stop_update machinery).

    ``is_grad_scaled_by_nranks=True`` (default) matches this build's dp
    semantics: gradients arrive rank-AVERAGED, so the global norm is used
    as-is; pass False only if your grads are rank-summed, and the norm is
    divided by the world size before clipping (ref :124)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 alignment=128, use_master_param_norm=True,
                 gradient_accumulation_steps=1, use_master_acc_grad=True,
                 max_global_grad_norm=-1.0, nproc_per_node=None,
                 use_hierarchical_allreduce=False, name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip, name)
        assert gradient_accumulation_steps >= 1
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn
        self._max_global_grad_norm = float(max_global_grad_norm)
        self._is_grad_scaled_by_nranks = is_grad_scaled_by_nranks
        self._acc_steps = int(gradient_accumulation_steps)
        self._acc_count = 0
        self._acc_store: dict[int, Tensor] = {}
        # recorded-for-parity knobs (see class docstring for why they
        # collapse on TPU): alignment is a CUDA flat-buffer concern,
        # hierarchical allreduce is an XLA scheduling decision
        self._clip_after_allreduce = clip_after_allreduce
        self._alignment = alignment
        self._use_master_param_norm = use_master_param_norm
        self._use_master_acc_grad = use_master_acc_grad
        self._nproc_per_node = nproc_per_node
        self._use_hierarchical_allreduce = use_hierarchical_allreduce
        self._lamb_step_t = 0

    def _global_grad_scale(self, params_grads):
        if self._max_global_grad_norm <= 0:
            return None
        sq = jnp.zeros((), jnp.float32)
        for p, g in params_grads:
            if g is None:
                continue
            ga = g._read().astype(jnp.float32)
            s = jnp.sum(ga * ga)
            for row, off, n in getattr(p, "_tied_dup_slots", ()):
                dup = ga[row, off:off + n]
                s = s - jnp.sum(dup * dup)
            sq = sq + s
        norm = jnp.sqrt(sq)
        if not self._is_grad_scaled_by_nranks:
            from paddle_tpu.distributed import get_world_size
            norm = norm / max(get_world_size(), 1)
        limit = jnp.asarray(self._max_global_grad_norm, jnp.float32)
        return jnp.minimum(1.0, limit / jnp.maximum(norm, 1e-12))

    def step(self):
        # LAMB's trust ratio needs whole-parameter norms, so SelectedRows
        # (sparse embedding) grads densify up front — the reference's fused
        # kernel likewise only consumes flat dense grads
        from paddle_tpu.core.selected_rows import SelectedRows
        for p in self._all_params():
            if isinstance(p._grad, SelectedRows):
                p._grad = Tensor(p._grad.to_dense().astype(p._data.dtype),
                                 stop_gradient=True, _internal=True)
        self._acc_count += 1
        if self._acc_count < self._acc_steps:
            # accumulate and hold (ref stop_update): params untouched
            for p in self._all_params():
                if p._grad is None:
                    continue
                acc = self._acc_store.get(id(p))
                g = p._grad._read().astype(jnp.float32)
                self._acc_store[id(p)] = Tensor(
                    g if acc is None else acc._data + g, _internal=True)
                p._grad = None
            return
        self._acc_count = 0
        if self._acc_store:
            for p in self._all_params():
                acc = self._acc_store.get(id(p))
                if acc is None and p._grad is None:
                    continue
                tot = jnp.zeros((), jnp.float32) if acc is None else acc._data
                if p._grad is not None:
                    tot = tot + p._grad._read().astype(jnp.float32)
                p._grad = Tensor((tot / self._acc_steps).astype(
                    p._grad._data.dtype if p._grad is not None
                    else jnp.float32), stop_gradient=True, _internal=True)
            self._acc_store.clear()
        super().step()

    def _append_optimize_op(self, p, grad, lr, weight_decay, t=None):
        from paddle_tpu.optimizer.optimizers import _lamb_update
        if self._exclude_fn is not None and self._exclude_fn(p):
            weight_decay = 0.0
        if not hasattr(self, "_clip_scale_cache") or \
                self._clip_scale_cache[0] is not self._step_tensor._data:
            scale = self._global_grad_scale(
                [(q, q._grad) for q in self._all_params()])
            self._clip_scale_cache = (self._step_tensor._data, scale)
        scale = self._clip_scale_cache[1]
        m = self._accumulator("moment1", p, dtype=jnp.float32)
        v = self._accumulator("moment2", p, dtype=jnp.float32)
        src = self._update_src(p)
        g = grad._read()
        if scale is not None:
            g = (g.astype(jnp.float32) * scale).astype(g.dtype)
        new_p, new_m, new_v = _lamb_update(
            src._read(), g, m._read(), v._read(),
            jnp.asarray(lr, jnp.float32),
            jnp.asarray(self._beta1, jnp.float32),
            jnp.asarray(self._beta2, jnp.float32),
            jnp.asarray(self._epsilon, jnp.float32),
            jnp.asarray(t if t is not None else self._global_step,
                        jnp.float32),
            jnp.asarray(weight_decay, jnp.float32))
        self._commit(p, src, new_p)
        m._write(new_m)
        v._write(new_v)
