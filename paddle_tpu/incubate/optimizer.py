"""incubate optimizers: LookAhead, ModelAverage
(ref `python/paddle/incubate/optimizer/lookahead.py` :30,
`modelaverage.py` :31).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = ["LookAhead", "ModelAverage"]


from paddle_tpu.distributed.fleet.meta_optimizers import _MetaOptimizerBase


class LookAhead(_MetaOptimizerBase):
    """k-step lookahead: slow weights pulled toward the fast optimizer's
    weights every k steps (Zhang et al.; ref lookahead.py:30). Delegation /
    minimize ride the shared meta-optimizer base."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        if int(k) < 1:
            raise ValueError("k should be >= 1")
        super().__init__(inner_optimizer)
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_num = 0
        self._slow = {}

    @property
    def inner_optimizer(self):
        return self._inner_opt

    def step(self):
        params = self._inner_opt._parameter_list
        if self._step_num == 0:
            for i, p in enumerate(params):
                self._slow[i] = p._data
        self._inner_opt.step()
        self._step_num += 1
        if self._step_num % self.k == 0:
            for i, p in enumerate(params):
                slow = self._slow[i] + self.alpha * (p._data - self._slow[i])
                self._slow[i] = slow
                p._write(slow.astype(p._data.dtype))

    def state_dict(self):
        sd = self._inner_opt.state_dict()
        sd["@LookAhead.slow"] = {i: np.asarray(v)
                                 for i, v in self._slow.items()}
        sd["@LookAhead.step"] = self._step_num
        return sd

    def set_state_dict(self, state):
        state = dict(state)   # don't mutate the caller's dict
        slow = state.pop("@LookAhead.slow", None)
        self._step_num = state.pop("@LookAhead.step", 0)
        if slow is not None:
            self._slow = {i: jnp.asarray(v) for i, v in slow.items()}
        self._inner_opt.set_state_dict(state)


class ModelAverage:
    """Maintains a running average of parameters for evaluation
    (ref modelaverage.py:31): `apply()` swaps averaged weights in,
    `restore()` swaps training weights back."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError("parameters is required")
        self._params = list(parameters)
        self.rate = float(average_window_rate)
        self.min_window = int(min_average_window)
        self.max_window = int(max_average_window)
        self._sum = [jnp.zeros_like(p._data) for p in self._params]
        self._count = 0
        self._backup = None

    def step(self):
        """Accumulate the current weights (call after each optimizer.step)."""
        if self._count >= self.max_window:
            # restart the window like the reference's sum rotation
            shrink = max(self.min_window, int(self.rate * self._count))
            scale = shrink / max(self._count, 1)
            self._sum = [s * scale for s in self._sum]
            self._count = shrink
        self._sum = [s + p._data for s, p in zip(self._sum, self._params)]
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        """Swap averaged weights in (context-manager friendly)."""
        if self._count == 0:
            return self
        if self._backup is None:   # double-apply must not clobber the backup
            self._backup = [p._data for p in self._params]
        for p, s in zip(self._params, self._sum):
            p._write((s / self._count).astype(p._data.dtype))
        if not need_restore:
            self._backup = None
        return self

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p, b in zip(self._params, self._backup):
            p._write(b)
        self._backup = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.restore()
