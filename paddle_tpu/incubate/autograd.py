"""incubate.autograd — functional AD primitives (ref
`python/paddle/incubate/autograd/primapi.py` jvp/vjp and the Jacobian/Hessian
classes from `autograd/functional.py`).

The reference built a whole primitive-op AD system (orig2prim/prim2orig
transforms over a prim op set) because its static graph could not differentiate
twice; on a jax substrate these are direct calls into `jax.jvp`/`jax.vjp` —
the transform machinery *is* the substrate.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "forward_grad", "grad"]


def _unwrap(xs):
    single = isinstance(xs, Tensor)
    lst = [xs] if single else list(xs)
    return single, [t._data for t in lst]


def _wrap(arrs, single):
    ts = [Tensor(a, stop_gradient=True, _internal=True) for a in arrs]
    return ts[0] if single else ts


def _purify(func, single):
    def pure(*arrs):
        ts = [Tensor(a, stop_gradient=False, _internal=True) for a in arrs]
        from paddle_tpu.core.autograd import no_grad
        with no_grad():
            out = func(ts[0]) if single and len(ts) == 1 else func(*ts)
        if isinstance(out, (tuple, list)):
            return tuple(o._data for o in out)
        return out._data
    return pure


def jvp(func, xs, v=None):
    """Forward-mode: returns (outputs, JVP) (ref primapi.jvp)."""
    single, arrs = _unwrap(xs)
    if v is None:
        tangents = [jnp.ones_like(a) for a in arrs]
    else:
        _, tangents = _unwrap(v)
    pure = _purify(func, single)
    out, tangent_out = jax.jvp(pure, tuple(arrs), tuple(tangents))
    multi_out = isinstance(out, tuple)
    wrap_out = _wrap(list(out) if multi_out else [out], not multi_out)
    wrap_tan = _wrap(list(tangent_out) if multi_out else [tangent_out],
                     not multi_out)
    return wrap_out, wrap_tan


def vjp(func, xs, v=None):
    """Reverse-mode: returns (outputs, VJP) (ref primapi.vjp)."""
    single, arrs = _unwrap(xs)
    pure = _purify(func, single)
    out, vjp_fn = jax.vjp(pure, *arrs)
    if v is None:
        cot = jnp.ones_like(out) if not isinstance(out, tuple) else tuple(
            jnp.ones_like(o) for o in out)
    else:
        vs, varrs = _unwrap(v)
        cot = varrs[0] if vs else tuple(varrs)
    grads = vjp_fn(cot)
    multi_out = isinstance(out, tuple)
    wrap_out = _wrap(list(out) if multi_out else [out], not multi_out)
    return wrap_out, _wrap(list(grads), single)


def forward_grad(func, xs, v=None):
    """Alias of jvp returning only the tangent (ref primapi.forward_grad)."""
    return jvp(func, xs, v)[1]


def grad(func, xs, v=None):
    """Alias of vjp returning only the gradients (ref primapi.grad)."""
    return vjp(func, xs, v)[1]


class Jacobian:
    """Lazy Jacobian matrix (ref autograd/functional.py:Jacobian): index to
    materialize rows; `[:]` gives the full matrix."""

    def __init__(self, func, xs, is_batched=False):
        single, arrs = _unwrap(xs)
        pure = _purify(func, single)
        if is_batched:
            jac = jax.vmap(jax.jacrev(pure))(arrs[0])
        else:
            jac = jax.jacrev(pure)(*arrs) if len(arrs) == 1 else \
                jax.jacrev(pure, argnums=tuple(range(len(arrs))))(*arrs)
        self._jac = jnp.asarray(jac if not isinstance(jac, (tuple, list))
                                else jac[0])
        # flatten to 2-D (out_size, in_size) like the reference matrix view
        if not is_batched and self._jac.ndim > 2:
            half = self._jac.ndim // 2
            osz = int(np.prod(self._jac.shape[:half]))
            self._jac = self._jac.reshape(osz, -1)

    @property
    def shape(self):
        return list(self._jac.shape)

    def __getitem__(self, idx):
        return Tensor(self._jac[idx], _internal=True)

    def numpy(self):
        return np.asarray(self._jac)


class Hessian:
    """Lazy Hessian (ref autograd/functional.py:Hessian) for scalar-output
    functions."""

    def __init__(self, func, xs, is_batched=False):
        single, arrs = _unwrap(xs)
        pure = _purify(func, single)

        def scalar(*a):
            out = pure(*a)
            return out.reshape(())

        if is_batched:
            hes = jax.vmap(jax.hessian(scalar))(arrs[0])
        else:
            hes = jax.hessian(scalar)(*arrs)
        self._hes = jnp.asarray(hes)
        if not is_batched and self._hes.ndim > 2:
            n = int(np.sqrt(np.prod(self._hes.shape)))
            self._hes = self._hes.reshape(n, n)

    @property
    def shape(self):
        return list(self._hes.shape)

    def __getitem__(self, idx):
        return Tensor(self._hes[idx], _internal=True)

    def numpy(self):
        return np.asarray(self._hes)
