"""ASP — automatic structured (n:m) sparsity.

Rebuild of the reference's `python/paddle/incubate/asp/` (over
`fluid/contrib/sparsity/`: `calculate_density`, `prune_model` :~, `decorate`,
utils `create_mask`/`check_sparsity` with mask_1d / mask_2d algorithms).
On TPU there is no sparse tensor-core constraint, but the n:m pattern is still
the pruning contract users train against, and XLA benefits from the induced
zeros at int8 time; masks are applied as element multiplies and re-applied
after every optimizer step by the decorated optimizer (the reference's
OptimizerWithSparsityGuarantee).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = ["calculate_density", "create_mask", "check_sparsity", "prune_model",
           "decorate", "reset_excluded_layers", "set_excluded_layers"]

# mask lives on the parameter itself (p._asp_mask); this registry only lists
# pruned params for introspection and is weakref-safe against id() reuse
import weakref

_masks: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()
_excluded: set[str] = set()


def calculate_density(x) -> float:
    """Fraction of nonzeros (ref asp.py:calculate_density)."""
    arr = np.asarray(x.numpy() if hasattr(x, "numpy") else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def _mask_1d(mat, n, m):
    """Keep the n largest-|w| entries of every contiguous group of m along the
    last axis (ref sparsity/utils.py:get_mask_1d)."""
    shape = mat.shape
    flat = mat.reshape(-1, m)
    order = np.argsort(-np.abs(flat), axis=1)
    mask = np.zeros_like(flat, dtype=bool)
    rows = np.arange(flat.shape[0])[:, None]
    mask[rows, order[:, :n]] = True
    return mask.reshape(shape)


def _mask_2d_greedy(mat, n, m):
    """Greedy m x m block selection keeping n entries per row AND column
    (ref sparsity/utils.py:get_mask_2d_greedy)."""
    shape = mat.shape
    mat2 = mat.reshape(-1, shape[-1])
    rows, cols = mat2.shape
    mask = np.zeros_like(mat2, dtype=bool)
    for r0 in range(0, rows, m):
        for c0 in range(0, cols, m):
            blk = np.abs(mat2[r0:r0 + m, c0:c0 + m])
            bm = np.zeros_like(blk, dtype=bool)
            row_cnt = np.zeros(blk.shape[0], np.int64)
            col_cnt = np.zeros(blk.shape[1], np.int64)
            for idx in np.argsort(-blk, axis=None):
                i, j = divmod(int(idx), blk.shape[1])
                if row_cnt[i] < n and col_cnt[j] < n:
                    bm[i, j] = True
                    row_cnt[i] += 1
                    col_cnt[j] += 1
            # greedy can strand deficits (a row and column both short but
            # their crossing already blocked) — complete to exactly n per
            # row/col by best remaining candidates
            while (row_cnt < n).any():
                i = int(np.argmin(row_cnt))
                avail = np.where((~bm[i]) & (col_cnt < n))[0]
                if len(avail):
                    j = avail[np.argmax(blk[i, avail])]
                    bm[i, j] = True
                    row_cnt[i] += 1
                    col_cnt[j] += 1
                    continue
                # stranded: row i's remaining slots all sit on full columns.
                # Augment: move a selected cell (r, j2) to (r, j_deficit),
                # freeing column j2 for row i.
                j_def = int(np.argmin(col_cnt))
                moved = False
                for j2 in np.where(~bm[i])[0]:
                    rs = np.where(bm[:, j2] & ~bm[:, j_def])[0]
                    if len(rs):
                        r = int(rs[0])
                        bm[r, j2] = False
                        bm[r, j_def] = True
                        col_cnt[j2] -= 1
                        col_cnt[j_def] += 1
                        bm[i, j2] = True
                        row_cnt[i] += 1
                        col_cnt[j2] += 1
                        moved = True
                        break
                if not moved:
                    break
            mask[r0:r0 + m, c0:c0 + m] = bm
    return mask.reshape(shape)


_ALGOS = {"mask_1d": _mask_1d, "mask_2d_greedy": _mask_2d_greedy,
          "mask_2d_best": _mask_2d_greedy}


def create_mask(mat, func_name="mask_1d", n=2, m=4):
    """Boolean n:m mask for a 2-D (or trailing-dim-divisible) weight."""
    arr = np.asarray(mat.numpy() if hasattr(mat, "numpy") else mat)
    if arr.shape[-1] % m != 0:
        raise ValueError(f"last dim {arr.shape[-1]} not divisible by m={m}")
    return _ALGOS[func_name](arr, n, m)


def check_sparsity(mat, n=2, m=4, func_name="mask_1d"):
    """True iff the matrix already satisfies the n:m pattern
    (ref sparsity/utils.py:check_sparsity)."""
    arr = np.asarray(mat.numpy() if hasattr(mat, "numpy") else mat)
    if arr.shape[-1] % m != 0:
        return False
    nz = (arr.reshape(-1, m) != 0).sum(axis=1)
    return bool(np.all(nz <= n))


def set_excluded_layers(param_names, main_program=None):
    """Skip these parameter names during pruning (ref asp.py)."""
    _excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def _prunable(layer, name, p):
    import paddle_tpu.nn as nn
    if name in _excluded or p.name in _excluded:
        return False
    if p.ndim < 2:
        return False
    # the reference prunes FC and conv weights
    return isinstance(layer, (nn.Linear, nn.Conv2D, nn.Conv1D, nn.Conv3D))


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m pruning to every supported layer's weight and remember the
    masks so `decorate`d optimizers re-impose them after each step
    (ref asp.py:prune_model)."""
    from paddle_tpu.core.tensor import Tensor
    masks = {}
    for lname, layer in model.named_sublayers():
        w = getattr(layer, "weight", None)
        if w is None or not _prunable(layer, lname, w):
            continue
        arr = np.asarray(w.numpy())
        flat2d = arr.reshape(arr.shape[0], -1) if arr.ndim > 2 else arr
        if flat2d.shape[-1] % m != 0:
            continue
        mask = _ALGOS[mask_algo](flat2d, n, m).reshape(arr.shape)
        w._write(jnp.asarray(arr * mask))
        if with_mask:
            w._asp_mask = jnp.asarray(mask, arr.dtype)
            masks[lname] = w._asp_mask
            _masks[lname] = w
    return masks


class OptimizerWithSparsityGuarantee:
    """Re-applies the pruning masks after every step
    (ref asp.py:OptimizerWithSparsityGuarantee)."""

    def __init__(self, optimizer):
        self._inner_opt = optimizer

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()
        for p in self._inner_opt._parameter_list:
            mask = getattr(p, "_asp_mask", None)
            if mask is not None:
                p._write(p._data * mask.astype(p._data.dtype))

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._inner_opt._parameter_list]


def decorate(optimizer):
    """Wrap an optimizer with the sparsity guarantee (ref asp.py:decorate)."""
    return OptimizerWithSparsityGuarantee(optimizer)
