"""Test-support machinery that ships with the package (not under tests/):
deterministic fault injection (`paddle_tpu.testing.faults`) used by the
chaos suite, the overload bench rung, and ops drills against live
deployments (docs/ROBUSTNESS.md)."""
