"""Deterministic fault injection for the serving stack.

Failure handling that is only exercised by hand-written kill tests rots:
the paths that matter — a replica dying mid-decode, a socket dropping
mid-request, the page pool running dry — fire rarely in CI and never
deterministically. This module gives the stack NAMED injection sites that
tests (tests/test_chaos.py), the overload bench rung, and ops drills can
arm on demand:

    from paddle_tpu.testing import faults
    with faults.scoped("engine.step_delay", delay_s=0.2, times=3):
        ...   # the next 3 engine steps each stall 200 ms

or via the environment for out-of-process drills
(``PADDLE_FAULTS="engine.step_delay:delay_s=0.2:times=3,engine.crash"``).

Design rules (docs/ROBUSTNESS.md "Fault sites"):

- **Zero overhead when off.** Every call site guards on the module-level
  ``ENABLED`` flag (``faults.ENABLED and faults.fire(site)``), so the
  production hot path pays one attribute read and a falsy branch — no
  dict lookup, no lock.
- **Deterministic.** A site fires exactly ``times`` times (−1 =
  unlimited) in arming order; no randomness, no clocks. Chaos tests
  assert on exact fire counts (`fired(site)`).
- **Typed actions.** A site can sleep (``delay_s``), raise (``exc`` — a
  class, instantiated with a message naming the site), or simply report
  that it fired (the caller implements the fault, e.g. "return None from
  alloc"). `FaultInjected` is the default exception for crash sites so
  post-mortems distinguish injected failures from organic ones.

Sites currently wired (the catalog lives in docs/ROBUSTNESS.md):

========================  ====================================================
``engine.step_delay``     `DecodeEngine.step` sleeps ``delay_s`` (slow-device
                          / long-step simulation; deadline + watchdog tests)
``engine.crash``          `DecodeEngine.step` raises (engine-thread death;
                          the serve loop must abort every waiter)
``engine.pool_pressure``  `PageAllocator.alloc` reports exhaustion (forced
                          page-pool pressure without a giant workload)
``bench.preflight``       bench.py's backend preflight probe fails on its
                          first-use op (arm with ``exc=``; ``times=1``
                          lets the CPU re-probe succeed) — drives the
                          dead-backend-falls-back-to-CPU-rungs regression
                          test for the BENCH_r05 ``parsed:null`` shape
``serve.slow_read``       serve's client loop stalls ``delay_s`` before
                          reading a request body (slow-client simulation)
``serve.socket_drop``     serve's client loop drops the connection before
                          answering (network partition mid-request)
``serve.stream_drop``     serve's OP_PREFILL record loop drops the
                          connection MID-STREAM (prefill-worker death in
                          disaggregated serving; the router must fall
                          back to symmetric prefill and the decode side
                          must discard the partial pages cleanly)
``router.stale_directory``  the router's prefix-affinity lookup routes on
                          a deliberately STALE directory entry (fleet
                          directory staleness drill: the worker just
                          prefills the whole prompt — affinity is an
                          optimization, never a correctness dependency)
``kvtier.spill_fail``     the engine's prefix-page spill to the host/disk
                          tier fails (`DecodeEngine._spill_pages`): the
                          eviction degrades to a plain discard —
                          ``engine.kvtier.spill_fail`` counts it, the
                          pool reclaim NEVER fails
``kvtier.disk_corrupt``   the disk-tier read path treats the entry as
                          rotten (`kv_tiers.KVTierStore.get`): a typed
                          refusal counted in ``engine.kvtier.refusals``,
                          reported upward as a plain MISS — the request
                          cold-prefills, never errors
``kvtier.reupload_fail``  the batched tier re-upload into fresh pool
                          pages fails (`DecodeEngine._tier_reupload`):
                          the request keeps its fresh pages and
                          cold-prefills the whole prompt
                          (``engine.kvtier.reupload_fail``)
``train.step_nan``        `ScanTrainStep.step` feeds a NaN through the
                          program's finite-reduce INPUT — the bad-step skip
                          path runs in the warm program (no recompile)
``ckpt.write_truncate``   `save_sharded` truncates the shard file it just
                          wrote (torn-write simulation; load must refuse by
                          checksum with `CheckpointCorrupt`)
``ckpt.crash_between_shards``  `save_sharded` dies between shard files (the
                          checkpoint must stay INVISIBLE: no index, no
                          COMPLETE, LATEST untouched)
``ckpt.barrier_timeout``  the multi-host checkpoint publication barrier
                          times out (a peer died between its shard writes
                          and COMPLETE): every survivor raises typed
                          `PeerLost`, the checkpoint stays invisible
                          fleet-wide (`train/fault_tolerance.py`)
``train.peer_dead``       the armed elastic-training rank SIGKILLs itself
                          at the ``times``-th step boundary (deterministic
                          spot reclaim; survivors must detect via
                          heartbeats — `train/elastic.py`)
``train.collective_stall``  a rank stalls ``delay_s`` INSIDE the eager KV
                          allgather before publishing its contribution
                          (wedged-peer simulation: its heartbeat goes
                          stale and survivors raise typed `PeerLost`)
``loader.stall``          `DataLoader`'s worker fetch behaves as if the
                          stall window elapsed: first fire re-enqueues the
                          in-flight batches (one bounded retry); a second
                          fire WITHOUT a delivery in between raises typed
                          `DataLoaderStalled` (any delivery re-arms the
                          retry — "twice" means twice in a row)
========================  ====================================================
"""
from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager

__all__ = ["ENABLED", "FaultInjected", "arm", "disarm", "fire", "fired",
           "remaining", "scoped", "arm_from_env"]

# fast-path flag: call sites guard on this BEFORE calling fire(), so a
# production process with no faults armed never takes the lock below
ENABLED = False

_lock = threading.Lock()
_armed: dict[str, "_Fault"] = {}
_fired: dict[str, int] = {}


class FaultInjected(RuntimeError):
    """Raised by crash-style fault sites — distinguishable from organic
    failures in logs, watchdog dumps, and chaos-test assertions."""


class _Fault:
    __slots__ = ("times", "delay_s", "exc")

    def __init__(self, times: int, delay_s: float, exc):
        self.times = times          # remaining fires; -1 = unlimited
        self.delay_s = delay_s
        self.exc = exc              # exception CLASS to raise, or None


def arm(site: str, times: int = 1, delay_s: float = 0.0, exc=None):
    """Arm ``site`` to fire ``times`` times (−1 = until disarmed). Each
    fire sleeps ``delay_s`` then raises ``exc(...)`` if given, else
    returns True to the call site."""
    global ENABLED
    if exc is not None and not (isinstance(exc, type)
                                and issubclass(exc, BaseException)):
        raise TypeError(f"exc must be an exception class, got {exc!r}")
    with _lock:
        _armed[site] = _Fault(int(times), float(delay_s), exc)
        _fired.setdefault(site, 0)
        ENABLED = True


def disarm(site: str | None = None):
    """Disarm one site (or all of them) and drop the fast-path flag when
    nothing stays armed. Lifetime fire counts are kept — `fired` reports
    them so tests can delta around a scope."""
    global ENABLED
    with _lock:
        if site is None:
            _armed.clear()
        else:
            _armed.pop(site, None)
        ENABLED = bool(_armed)


def fire(site: str) -> bool:
    """Hot-path check: did ``site`` fire? Only call behind an ``ENABLED``
    guard. Applies the armed delay, raises the armed exception, or
    returns True; returns False when the site is not armed (or spent)."""
    with _lock:
        f = _armed.get(site)
        if f is None or f.times == 0:
            return False
        if f.times > 0:
            f.times -= 1
        _fired[site] = _fired.get(site, 0) + 1
        delay_s, exc = f.delay_s, f.exc
    if delay_s > 0:
        time.sleep(delay_s)
    if exc is not None:
        raise exc(f"fault injected at {site}")
    return True


def fired(site: str) -> int:
    """Lifetime fire count for ``site`` (0 if it never fired)."""
    with _lock:
        return _fired.get(site, 0)


def remaining(site: str):
    """Charges left on an ARMED site (−1 = unlimited), or None when the
    site is not armed. Lets a call site act on the LAST charge — e.g.
    ``train.peer_dead:times=k`` kills its rank at the k-th step boundary
    (`train/elastic.py`), not the first."""
    with _lock:
        f = _armed.get(site)
        return None if f is None else f.times


@contextmanager
def scoped(site: str, times: int = 1, delay_s: float = 0.0, exc=None):
    """Arm ``site`` for the body, disarm on exit — the chaos-test idiom
    (a failing assertion must never leave a fault armed for the next
    test)."""
    arm(site, times=times, delay_s=delay_s, exc=exc)
    try:
        yield
    finally:
        disarm(site)


def arm_from_env(spec: str | None = None):
    """Parse ``PADDLE_FAULTS`` (or an explicit spec): comma-separated
    sites, each ``site[:key=val[:key=val...]]`` with keys ``times``,
    ``delay_s``, ``exc`` (a builtin exception name, or ``FaultInjected``).
    Example: ``engine.step_delay:delay_s=0.2:times=3,engine.crash:exc=\
FaultInjected``. Unknown keys raise — a typo'd drill must fail loudly,
    not silently inject nothing."""
    spec = os.environ.get("PADDLE_FAULTS", "") if spec is None else spec
    for entry in filter(None, (s.strip() for s in spec.split(","))):
        parts = entry.split(":")
        site, kw = parts[0], {}
        for p in parts[1:]:
            k, _, v = p.partition("=")
            if k == "times":
                kw["times"] = int(v)
            elif k == "delay_s":
                kw["delay_s"] = float(v)
            elif k == "exc":
                exc = {"FaultInjected": FaultInjected}.get(v) \
                    or getattr(__import__("builtins"), v, None)
                if not (isinstance(exc, type)
                        and issubclass(exc, BaseException)):
                    raise ValueError(f"PADDLE_FAULTS: unknown exception "
                                     f"{v!r} for site {site!r}")
                kw["exc"] = exc
            else:
                raise ValueError(
                    f"PADDLE_FAULTS: unknown key {k!r} in {entry!r} "
                    f"(have times/delay_s/exc)")
        arm(site, **kw)


if os.environ.get("PADDLE_FAULTS"):
    arm_from_env()
