"""Soak harness: the deterministic fault-injection answer to soak testing.

Classic soak testing hammers a system with random load for hours and hopes
a race shows up. This repo's chaos suites are DETERMINISTIC (named fault
sites, exact fire counts — `testing/faults.py`), so the soak equivalent is
repetition with ROTATED orderings: run the chaos suites N times, each
iteration starting from a different suite, so cross-suite residue (a
leaked thread, an unarmed-but-counted fault, a metrics baseline
assumption) gets every adjacency. On the FIRST failure the harness dumps
the flight-recorder ring + the metrics snapshot to JSON — the post-mortem
a flaky CI retry throws away.

    python -m paddle_tpu.testing.soak --iterations 5
    python -m paddle_tpu.testing.soak --micro          # pytest-free drill

Two layers:

- `run()` — pytest over the chaos suites (serving chaos, train chaos,
  elastic multi-host training, migration, control-plane HA,
  disaggregated serving), suite order rotated per iteration.
- `run_micro()` — a self-contained pytest-free micro-drill (used by
  ``bench --smoke`` at 2 iterations, key ``soak_ok``): one tiny engine
  per iteration driven through a rotated ordering of fault scenarios
  (slow steps, transient pool pressure, wire-blob corruption, page-stream
  corruption, peer-death liveness), asserting typed outcomes and a
  page-clean pool each time.

Both dump the ring via `dump_ring()` on first failure and stop — a soak
failure is a real bug with a fresh post-mortem, not a statistic.
"""
from __future__ import annotations

import argparse
import os

__all__ = ["CHAOS_SUITES", "rotated", "dump_ring", "peer_lost_drill",
           "run", "run_micro", "main"]

# the chaos suites, in their canonical order (rotation starts here)
CHAOS_SUITES = (
    "tests/test_chaos.py",
    "tests/test_train_chaos.py",
    "tests/test_train_elastic.py",
    "tests/test_migration.py",
    "tests/test_control_plane.py",
    "tests/test_disagg.py",
    "tests/test_fleet_observability.py",
    "tests/test_kv_tiers.py",
    "tests/test_slo_usage.py",
)


def rotated(seq, i: int) -> list:
    """``seq`` rotated left by ``i`` (mod len) — iteration i's ordering."""
    seq = list(seq)
    if not seq:
        return seq
    i %= len(seq)
    return seq[i:] + seq[:i]


def dump_ring(out_dir: str = ".", label: str = "soak") -> str:
    """Write the flight-recorder ring + the metrics snapshot to a JSON
    post-mortem file and return its path. Delegates to the shared
    artifact writer (`observability/flight_recorder.py:dump_ring`), so
    the soak dump, the watchdog dump, and the liveness PeerLost dump all
    share one shape: {label, events, metrics}."""
    from paddle_tpu.observability.flight_recorder import dump_ring as _dump
    return _dump(label, out_dir=out_dir)


def run(iterations: int = 3, suites=None, out_dir: str = ".",
        pytest_args=()) -> int:
    """Run the chaos suites ``iterations`` times, suite order rotated per
    iteration. Stops at the FIRST failing iteration: dumps the flight
    ring to ``out_dir`` and returns the pytest exit code (0 = every
    iteration green)."""
    import pytest
    suites = list(CHAOS_SUITES if suites is None else suites)
    for i in range(int(iterations)):
        order = rotated(suites, i)
        print(f"SOAK iteration {i + 1}/{iterations}: {' '.join(order)}",
              flush=True)
        rc = pytest.main([*order, "-q", "-p", "no:cacheprovider",
                          "-p", "no:randomly", *pytest_args])
        if rc != 0:
            path = dump_ring(out_dir)
            print(f"SOAK FAILED at iteration {i + 1}; "
                  f"flight ring dumped to {path}", flush=True)
            return int(rc) or 1
    print(f"SOAK OK: {iterations} iteration(s)", flush=True)
    return 0


def peer_lost_drill(out_dir=None) -> bool:
    """One typed-PeerLost conversion on a 2-rank heartbeat board: the
    peer beats once, goes silent past the deadline, and ``check()`` must
    raise typed `PeerLost` (docs/ROBUSTNESS.md "Multi-host training").
    Returns True when the typed error fired. The ONE implementation the
    micro-drill scenario and ``bench --smoke``'s ``peer_lost_typed_ok``
    key both run — the contract cannot drift between them."""
    import json
    import tempfile
    import time

    from paddle_tpu.distributed.liveness import LivenessMonitor, PeerLost
    d = out_dir or tempfile.mkdtemp(prefix="peer_lost_drill_")
    mon = LivenessMonitor(d, rank=0, world=2, deadline_s=0.02)
    # the beat lands AFTER the monitor's birth (pre-birth beats read as
    # stale files from a previous incarnation and fall under grace)
    with open(os.path.join(d, "hb-1.json"), "w") as f:
        json.dump({"rank": 1, "step": 3, "t": time.time()}, f)
    mon.beat(4)
    mon.check()                         # fresh peer: healthy
    time.sleep(0.06)                    # peer goes silent past deadline
    try:
        mon.check(context="peer-lost drill")
    except PeerLost:
        return True
    return False


# ------------------------------------------------------------ micro drill


def _micro_scenarios():
    """The pytest-free drill scenarios. Each takes a fresh tiny engine
    and must leave it page-clean; order is rotated per iteration."""
    import numpy as np

    from paddle_tpu.testing import faults

    def slow_steps(eng):
        # slowed steps must change nothing but wall clock
        with faults.scoped("engine.step_delay", times=3, delay_s=0.005):
            r = eng.submit(np.arange(5, dtype=np.int32), 3,
                           request_key=bytes(range(16)))
            eng.run_until_idle(max_steps=64)
            assert r.result(timeout=10).shape == (8,)
        # and the idempotency replay answers without re-running
        r2 = eng.submit(np.arange(5, dtype=np.int32), 3,
                        request_key=bytes(range(16)))
        assert r2 is r

    def pool_pressure(eng):
        # one injected allocation failure defers admission while another
        # request occupies the engine; both still complete (prompt sizes
        # fit an 8-position model so bench --smoke can pass its own)
        a = eng.submit(np.arange(4, dtype=np.int32), 4)
        eng.step()
        with faults.scoped("engine.pool_pressure", times=1):
            b = eng.submit(np.arange(1, 5, dtype=np.int32), 3)
            eng.run_until_idle(max_steps=64)
        assert a.result(timeout=10) is not None
        assert b.result(timeout=10) is not None

    def blob_corrupt(eng):
        # a bit-flipped handoff blob must refuse typed, never decode
        from paddle_tpu.inference.engine import KVHandoff
        from paddle_tpu.inference.errors import HandoffCorrupt
        h = eng.prefill_export(np.arange(6, dtype=np.int32))
        blob = h.pack()
        KVHandoff.unpack(blob)                  # clean round trip
        bad = bytearray(blob)
        bad[-9] ^= 0x20
        try:
            KVHandoff.unpack(bytes(bad))
        except HandoffCorrupt:
            return
        raise AssertionError("corrupt blob was not refused")

    def stream_corrupt(eng):
        # the disaggregated page stream: a clean record sequence
        # assembles bit-identical, and a bit flip in a MID-STREAM chunk
        # refuses typed before any page is adopted
        from paddle_tpu.inference.errors import HandoffCorrupt
        from paddle_tpu.serving.disagg import (KVStreamAssembler,
                                               stream_records)
        h = eng.prefill_export(np.arange(6, dtype=np.int32))
        recs = stream_records(h, pages_per_batch=1)
        asm = KVStreamAssembler()
        out = None
        for r in recs:
            out = asm.feed(r)
        assert out is not None and np.array_equal(out.prompt, h.prompt)
        asm2 = KVStreamAssembler()
        asm2.feed(recs[0])
        bad = bytearray(recs[1])
        bad[-5] ^= 0x04
        try:
            asm2.feed(bytes(bad))
        except HandoffCorrupt:
            return
        raise AssertionError("corrupt stream record was not refused")

    def peer_death(eng):
        # the multi-host liveness contract, engine-free: a 2-rank
        # heartbeat board whose peer went silent past the deadline must
        # raise typed PeerLost (never hang) — the shared drill bench
        # --smoke's `peer_lost_typed_ok` also runs
        del eng
        assert peer_lost_drill(), "silent peer was not typed PeerLost"

    return [slow_steps, pool_pressure, blob_corrupt, stream_corrupt,
            peer_death]


def run_micro(iterations: int = 2, model=None, out_dir: str = ".") -> int:
    """Self-contained soak drill (no pytest): per iteration, one tiny
    engine driven through a ROTATED ordering of the fault scenarios,
    pool asserted page-clean after each. Returns 0 on success; on the
    first failure dumps the flight ring and returns 1. ``model`` reuses
    a caller's tiny GPT (bench --smoke passes its own to skip a
    build)."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import DecodeEngine, EngineConfig

    if model is None:
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        paddle.seed(17)
        model = GPTForCausalLM(GPTConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
            intermediate_size=64, max_position_embeddings=32,
            hidden_dropout=0.0, attention_dropout=0.0))
    scenarios = _micro_scenarios()
    for i in range(int(iterations)):
        eng = DecodeEngine(model, EngineConfig(page_size=4, max_slots=2,
                                               min_bucket=8))
        try:
            for scenario in rotated(scenarios, i):
                scenario(eng)
                assert eng.allocator.free_pages \
                    == eng.allocator.num_pages - 1, (
                        f"{scenario.__name__} leaked pages")
        except Exception as e:  # noqa: BLE001 — dump, then report
            path = dump_ring(out_dir, label="soak_micro")
            print(f"SOAK MICRO FAILED at iteration {i + 1} "
                  f"({type(e).__name__}: {e}); ring dumped to {path}",
                  flush=True)
            return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "paddle_tpu.testing.soak",
        description="repeat the deterministic chaos suites with rotated "
                    "orderings; dump the flight ring on first failure")
    ap.add_argument("--iterations", type=int, default=3)
    ap.add_argument("--suite", action="append", default=[],
                    help="suite path (repeatable; default: the chaos "
                         "suites)")
    ap.add_argument("--out-dir", default=".",
                    help="where a failure post-mortem JSON lands")
    ap.add_argument("--micro", action="store_true",
                    help="run the pytest-free micro drill instead")
    ap.add_argument("-k", default=None,
                    help="pytest -k selection forwarded to the suites")
    args = ap.parse_args(argv)
    if args.micro:
        return run_micro(iterations=args.iterations, out_dir=args.out_dir)
    extra = ("-k", args.k) if args.k else ()
    return run(iterations=args.iterations,
               suites=args.suite or None, out_dir=args.out_dir,
               pytest_args=extra)


if __name__ == "__main__":
    raise SystemExit(main())
