"""``paddle.audio.datasets`` (ref: `python/paddle/audio/datasets/` —
AudioClassificationDataset `dataset.py:29`, TESS `tess.py:26`, ESC50
`esc50.py:26`).

Zero-egress environment: datasets read from a LOCAL directory (pass
``data_dir``, or set ``PADDLE_AUDIO_DATA_HOME``); when the files are
missing the error names the archive the reference would download, instead
of silently fetching.
"""
from __future__ import annotations

import collections
import os

import numpy as np

from paddle_tpu.io import Dataset
from paddle_tpu.audio.features import (
    MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram)

__all__ = ["AudioClassificationDataset", "TESS", "ESC50"]

feat_classes = {
    "raw": None,
    "melspectrogram": MelSpectrogram,
    "mfcc": MFCC,
    "logmelspectrogram": LogMelSpectrogram,
    "spectrogram": Spectrogram,
}


def _data_home(data_dir):
    return data_dir or os.environ.get(
        "PADDLE_AUDIO_DATA_HOME",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle", "datasets"))


class AudioClassificationDataset(Dataset):
    """ref `dataset.py:29`: (waveform-or-feature, label) pairs over wav
    files, with the feature extractor chosen by ``feat_type``."""

    def __init__(self, files, labels, feat_type="raw", sample_rate=None,
                 **kwargs):
        super().__init__()
        if feat_type not in feat_classes:
            raise RuntimeError(
                f"Unknown feat_type: {feat_type}, must be one of "
                f"{list(feat_classes)}")
        self.files = list(files)
        self.labels = list(labels)
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self._feat_kwargs = kwargs
        self._extractors = {}       # keyed by sample rate: mixed-rate
        # datasets must not reuse a filterbank built for another rate

    def _feature(self, waveform, sr):
        cls = feat_classes[self.feat_type]
        if cls is None:
            return waveform
        rate = self.sample_rate or sr
        ex = self._extractors.get(rate)
        if ex is None:
            if cls is Spectrogram:      # rate-free transform: no sr param
                ex = cls(**self._feat_kwargs)
            else:
                ex = cls(sr=rate, **self._feat_kwargs)
            self._extractors[rate] = ex
        out = ex(waveform.unsqueeze(0))
        return out.squeeze(0)

    def __getitem__(self, idx):
        from paddle_tpu.audio import backends
        waveform, sr = backends.load(self.files[idx], channels_first=False)
        if waveform.shape[-1] > 1:
            waveform = waveform.mean(axis=-1)   # downmix — interleaving
            # channels via reshape would corrupt the signal
        else:
            waveform = waveform.reshape([-1])   # mono [time]
        return self._feature(waveform, sr), np.int64(self.labels[idx])

    def __len__(self):
        return len(self.files)


class TESS(AudioClassificationDataset):
    """ref `tess.py:26` — 2800 emotional-speech wavs, 7 classes, n-fold
    split by file order."""

    label_list = ["angry", "disgust", "fear", "happy", "neutral", "ps",
                  "sad"]
    audio_path = "TESS_Toronto_emotional_speech_set"
    archive_url = ("https://bj.bcebos.com/paddleaudio/datasets/"
                   "TESS_Toronto_emotional_speech_set.zip")
    meta_info = collections.namedtuple("META_INFO",
                                       ("speaker", "word", "emotion"))

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 data_dir=None, **kwargs):
        assert mode in ("train", "dev"), mode
        assert isinstance(n_folds, int) and n_folds >= 1
        assert split in range(1, n_folds + 1)
        files, labels = self._get_data(mode, n_folds, split, data_dir)
        super().__init__(files=files, labels=labels, feat_type=feat_type,
                         **kwargs)

    def _get_data(self, mode, n_folds, split, data_dir):
        root = os.path.join(_data_home(data_dir), self.audio_path)
        if not os.path.isdir(root):
            raise FileNotFoundError(
                f"TESS data not found under {root}; this environment does "
                f"not download — fetch {self.archive_url} and unzip it "
                "there (or pass data_dir=)")
        wav_files = []
        for r, _, files in os.walk(root):
            wav_files.extend(os.path.join(r, f) for f in sorted(files)
                             if f.endswith(".wav"))
        files, labels = [], []
        for i, f in enumerate(sorted(wav_files)):
            emotion = os.path.basename(f)[:-4].split("_")[-1].lower()
            if emotion not in self.label_list:
                continue
            fold = i % n_folds + 1
            if (mode == "train") == (fold != split):
                files.append(f)
                labels.append(self.label_list.index(emotion))
        return files, labels


class ESC50(AudioClassificationDataset):
    """ref `esc50.py:26` — 2000 environmental sounds, 50 classes, the
    meta CSV's fold column drives the train/dev split."""

    audio_path = os.path.join("ESC-50-master", "audio")
    meta_path = os.path.join("ESC-50-master", "meta", "esc50.csv")
    archive_url = ("https://bj.bcebos.com/paddleaudio/datasets/"
                   "ESC-50-master.zip")
    meta_info = collections.namedtuple(
        "META_INFO", ("filename", "fold", "target", "category",
                      "esc10", "src_file", "take"))

    def __init__(self, mode="train", split=1, feat_type="raw",
                 data_dir=None, **kwargs):
        assert mode in ("train", "dev"), mode
        files, labels = self._get_data(mode, split, data_dir)
        super().__init__(files=files, labels=labels, feat_type=feat_type,
                         **kwargs)

    def _get_data(self, mode, split, data_dir):
        home = _data_home(data_dir)
        meta = os.path.join(home, self.meta_path)
        if not os.path.isfile(meta):
            raise FileNotFoundError(
                f"ESC-50 meta not found at {meta}; this environment does "
                f"not download — fetch {self.archive_url} and unzip it "
                "there (or pass data_dir=)")
        files, labels = [], []
        with open(meta) as rf:
            lines = rf.readlines()[1:]              # skip header
        for line in lines:
            m = self.meta_info(*line.strip().split(","))
            if (mode == "train") == (int(m.fold) != split):
                files.append(os.path.join(home, self.audio_path, m.filename))
                labels.append(int(m.target))
        return files, labels
