"""paddle.audio.features (ref `python/paddle/audio/features/layers.py`)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nn.layer import Layer
from paddle_tpu.core.autograd import apply
from paddle_tpu.audio import functional as AF


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length
        self.win_length = win_length
        self.window = window
        self.power = power
        self.center = center

    def forward(self, x):
        return AF.stft_power(x, self.n_fft, self.hop_length, self.win_length,
                             self.window, self.center, self.power)


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, n_mels=64, f_min=50.0,
                 f_max=None, htk=False, norm="slaney", dtype="float32"):
        super().__init__()
        self._spec = Spectrogram(n_fft, hop_length, win_length, window,
                                 power, center)
        self._fbank = AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min,
                                              f_max, htk, norm)

    def forward(self, x):
        spec = self._spec(x)                          # [..., bins, frames]
        fb = self._fbank

        def prim(s):
            return jnp.einsum("mf,...ft->...mt", jnp.asarray(fb), s)

        return apply(prim, spec, op_name="mel_spectrogram")


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", center=True, n_mels=64, f_min=50.0,
                 f_max=None, htk=False, norm="slaney", ref_value=1.0,
                 amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                   2.0, center, n_mels, f_min, f_max, htk,
                                   norm)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return AF.power_to_db(self._mel(x), self.ref_value, self.amin,
                              self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", center=True, n_mels=64,
                 f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._logmel = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, center, n_mels, f_min,
            f_max, htk, norm, ref_value, amin, top_db)
        self._dct = AF.create_dct(n_mfcc, n_mels)

    def forward(self, x):
        logmel = self._logmel(x)                      # [..., mels, frames]
        dct = self._dct

        def prim(s):
            return jnp.einsum("mk,...mt->...kt", jnp.asarray(dct), s)

        return apply(prim, logmel, op_name="mfcc")
