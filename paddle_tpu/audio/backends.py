"""``paddle.audio.backends`` — wav I/O (ref:
`python/paddle/audio/backends/wave_backend.py` info :37 / load :89 /
save :168, backend registry `init_backend.py:37`).

The built-in backend reads/writes PCM16 WAV through the stdlib ``wave``
module (exactly the reference's fallback backend); a ``soundfile`` backend
registers automatically when the optional package is importable.
"""
from __future__ import annotations

import wave as _wave
from dataclasses import dataclass

import numpy as np

__all__ = ["AudioInfo", "info", "load", "save",
           "list_available_backends", "get_current_backend", "set_backend"]


@dataclass
class AudioInfo:
    """ref `backends/backend.py` AudioInfo."""
    sample_rate: int
    num_frames: int
    num_channels: int
    bits_per_sample: int
    encoding: str = "PCM_S"


def _soundfile_available():
    try:
        import soundfile  # noqa: F401
        return True
    except Exception:
        return False


_BACKEND = "wave"


def list_available_backends():
    out = ["wave"]
    if _soundfile_available():
        out.append("soundfile")
    return out


def get_current_backend():
    return _BACKEND


def set_backend(backend_name):
    global _BACKEND
    if backend_name not in list_available_backends():
        raise NotImplementedError(
            f"backend {backend_name!r} unavailable; choose from "
            f"{list_available_backends()}")
    _BACKEND = backend_name


def info(filepath):
    """Signal info of a PCM WAV file (ref wave_backend.py:37)."""
    if _BACKEND == "soundfile":
        import soundfile as sf
        i = sf.info(str(filepath))
        return AudioInfo(int(i.samplerate), int(i.frames), int(i.channels),
                         16, i.subtype or "PCM_S")
    with _wave.open(str(filepath), "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Load a PCM16 WAV -> (Tensor, sample_rate) (ref wave_backend.py:89).

    normalize=True returns float32 in (-1, 1); False returns raw int16
    values (as float32, matching the reference). channels_first=True
    returns [channels, time].
    """
    import paddle_tpu as paddle

    if _BACKEND == "soundfile":
        import soundfile as sf
        data, sr = sf.read(str(filepath), dtype="int16")
        data = np.atleast_2d(data.T).T            # [frames, channels]
        channels = data.shape[1]
        frames = data.shape[0]
        audio = data.astype(np.float32)
    else:
        with _wave.open(str(filepath), "rb") as f:
            channels = f.getnchannels()
            sr = f.getframerate()
            frames = f.getnframes()
            if f.getsampwidth() != 2:
                raise NotImplementedError(
                    "only PCM16 WAV supported by the wave backend; "
                    "set_backend('soundfile') for other encodings")
            raw = f.readframes(frames)
        audio = np.frombuffer(raw, dtype=np.int16).astype(np.float32)
        audio = audio.reshape(frames, channels)
    if normalize:
        audio = audio / (2 ** 15)
    if num_frames != -1:
        audio = audio[frame_offset: frame_offset + num_frames, :]
    elif frame_offset:
        audio = audio[frame_offset:, :]
    if channels_first:
        audio = audio.T
    return paddle.to_tensor(np.ascontiguousarray(audio)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_16", bits_per_sample=16):
    """Save a waveform Tensor as WAV (ref wave_backend.py:168). The wave
    backend writes PCM_16; the soundfile backend honors other encodings."""
    arr = np.asarray(src._data if hasattr(src, "_data") else src)
    if _BACKEND == "soundfile":
        import soundfile as sf
        a = arr.T if channels_first and arr.ndim == 2 else arr
        sf.write(str(filepath), a, int(sample_rate), subtype=encoding)
        return
    if encoding != "PCM_16" or bits_per_sample != 16:
        raise NotImplementedError(
            "the wave backend writes PCM_16 only; "
            "set_backend('soundfile') for other encodings")
    if arr.ndim == 1:
        arr = arr[None, :] if channels_first else arr[:, None]
    if channels_first:
        arr = arr.T                                # -> [frames, channels]
    if arr.dtype.kind == "f":
        arr = np.clip(arr, -1.0, 1.0)
        arr = (arr * (2 ** 15 - 1)).astype(np.int16)
    else:
        arr = arr.astype(np.int16)
    with _wave.open(str(filepath), "wb") as f:
        f.setnchannels(arr.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(np.ascontiguousarray(arr).tobytes())
