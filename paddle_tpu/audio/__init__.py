"""paddle.audio (ref: `python/paddle/audio` — spectrogram/MFCC features).

Pure-jnp DSP: STFT via framing + rfft (XLA-compiled; the reference wraps
pocketfft), mel filterbank, DCT-II MFCC. Layers live in
``paddle.audio.features`` with the reference's class names.
"""
from paddle_tpu.audio import features  # noqa: F401
from paddle_tpu.audio import functional  # noqa: F401
from paddle_tpu.audio import backends  # noqa: F401
from paddle_tpu.audio import datasets  # noqa: F401
from paddle_tpu.audio.backends import info, load, save  # noqa: F401
