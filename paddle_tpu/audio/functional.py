"""paddle.audio.functional (ref `python/paddle/audio/functional/`)."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply
from paddle_tpu.ops.common import ensure_tensor


def hz_to_mel(f, htk=False):
    if htk:
        return 2595.0 * np.log10(1.0 + np.asarray(f) / 700.0)
    f = np.asarray(f, np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(f >= min_log_hz,
                    min_log_mel + np.log(f / min_log_hz) / logstep, mels)


def mel_to_hz(m, htk=False):
    if htk:
        return 700.0 * (10.0 ** (np.asarray(m) / 2595.0) - 1.0)
    m = np.asarray(m, np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(m >= min_log_mel,
                    min_log_hz * np.exp(logstep * (m - min_log_mel)), freqs)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney"):
    """[n_mels, n_fft//2 + 1] mel filterbank (ref compute_fbank_matrix)."""
    f_max = f_max or sr / 2.0
    fft_freqs = np.linspace(0, sr / 2.0, n_fft // 2 + 1)
    mel_pts = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                          n_mels + 2)
    hz_pts = mel_to_hz(mel_pts, htk)
    fdiff = np.diff(hz_pts)
    ramps = hz_pts[:, None] - fft_freqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    fb = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2: n_mels + 2] - hz_pts[:n_mels])
        fb *= enorm[:, None]
    return fb.astype(np.float32)


def get_window(window, win_length):
    n = np.arange(win_length)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * n / win_length)
    elif window in ("hamming",):
        w = 0.54 - 0.46 * np.cos(2 * np.pi * n / win_length)
    elif window in ("ones", "rect", "boxcar", None):
        w = np.ones(win_length)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return w.astype(np.float32)


def create_dct(n_mfcc, n_mels, norm="ortho"):
    """[n_mels, n_mfcc] DCT-II basis (ref create_dct)."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)
    dct = np.cos(np.pi / n_mels * (n[:, None] + 0.5) * k[None, :]) * 2.0
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(1.0 / (2.0 * n_mels))
    return dct.astype(np.float32)


def stft_power(x, n_fft=512, hop_length=None, win_length=None, window="hann",
               center=True, power=2.0):
    """[..., T] -> [..., n_fft//2+1, frames] power spectrogram."""
    x = ensure_tensor(x)
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    w = get_window(window, wl)
    if wl < n_fft:
        w = np.pad(w, (0, n_fft - wl))

    def prim(a):
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad, mode="reflect")
        T = a.shape[-1]
        n_frames = 1 + (T - n_fft) // hop
        idx = (jnp.arange(n_frames)[:, None] * hop +
               jnp.arange(n_fft)[None, :])
        frames = a[..., idx] * jnp.asarray(w)        # [..., frames, n_fft]
        spec = jnp.fft.rfft(frames, axis=-1)         # [..., frames, bins]
        mag = jnp.abs(spec) ** power
        return jnp.swapaxes(mag, -1, -2)             # [..., bins, frames]

    return apply(prim, x, op_name="spectrogram")


def power_to_db(x, ref_value=1.0, amin=1e-10, top_db=80.0):
    x = ensure_tensor(x)

    def prim(a):
        log_spec = 10.0 * jnp.log10(jnp.maximum(a, amin))
        log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec

    return apply(prim, x, op_name="power_to_db")
