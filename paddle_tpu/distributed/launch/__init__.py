"""paddle.distributed.launch (ref: `python/paddle/distributed/launch/main.py:18`,
CollectiveController at `launch/controllers/collective.py:21`)."""
from paddle_tpu.distributed.launch.main import launch  # noqa: F401
