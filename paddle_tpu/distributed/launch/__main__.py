from paddle_tpu.distributed.launch.main import launch

if __name__ == "__main__":
    launch()
