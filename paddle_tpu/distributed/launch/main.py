"""Launch CLI: multi-process training bringup.

Counterpart of `python -m paddle.distributed.launch`
(`python/paddle/distributed/launch/main.py:18`): the CollectiveController
(`launch/controllers/collective.py:21`) builds a Pod of per-rank Container
subprocesses with `PADDLE_TRAINER_*` env and per-rank log files, a rendezvous
master address, and a watch loop that tears the pod down on failure.

TPU-native differences: one process per HOST (a process owns all its local
chips via one jax runtime), so ``--nproc_per_node`` defaults to 1 and is only
raised for CPU-backend simulation/testing; the rendezvous "store" is the JAX
coordination service that ``init_parallel_env`` joins via
``jax.distributed.initialize`` (coordinator = ``PADDLE_MASTER``).

Usage:
    python -m paddle_tpu.distributed.launch \
        [--nnodes N] [--node_rank R] [--nproc_per_node P] \
        [--master host:port] [--log_dir dir] [--max_restarts K] \
        script.py [script args...]
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class Container:
    """One rank's subprocess (ref `launch/job/container.py`)."""

    def __init__(self, rank, cmd, env, log_path):
        self.rank = rank
        self.cmd = cmd
        self.env = env
        self.log_path = log_path
        self.proc = None
        self.log_file = None

    def start(self):
        self.log_file = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            self.cmd, env=self.env, stdout=self.log_file,
            stderr=subprocess.STDOUT)

    def poll(self):
        return self.proc.poll() if self.proc else None

    def terminate(self):
        if self.proc and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        if self.log_file:
            self.log_file.close()
            self.log_file = None


class Pod:
    """Per-node process group + watch loop (ref Controller at
    `launch/controllers/controller.py:161`; PodWatcher restart semantics)."""

    def __init__(self, containers, max_restarts=0, poll_interval=0.5,
                 elastic=None):
        self.containers = containers
        self.max_restarts = max_restarts
        self.poll_interval = poll_interval
        self.restarts = 0
        self.elastic = elastic

    def run(self):
        for c in self.containers:
            c.start()
        try:
            while True:
                codes = [c.poll() for c in self.containers]
                if all(code == 0 for code in codes):
                    return 0
                bad = [(c, code) for c, code in zip(self.containers, codes)
                       if code not in (None, 0)]
                if not bad and self.elastic is not None:
                    # heartbeat staleness counts as death (hung worker) —
                    # ref ElasticManager liveness watch
                    dead = self.elastic.dead_workers()
                    live_ranks = [c.rank for c, code in
                                  zip(self.containers, codes) if code is None]
                    dead = [r for r in dead if r in live_ranks]
                    if dead:
                        sys.stderr.write(
                            f"[launch] rank(s) {dead} heartbeat stale — "
                            "treating as failed\n")
                        # 124: conventional timeout exit code (a numeric code
                        # must flow to sys.exit / supervisor scripting)
                        bad = [(next(c for c in self.containers
                                     if c.rank == dead[0]), 124)]
                if bad:
                    c0, code = bad[0]
                    sys.stderr.write(
                        f"[launch] rank {c0.rank} exited with {code} "
                        f"(log: {c0.log_path})\n")
                    if self.restarts < self.max_restarts:
                        self.restarts += 1
                        sys.stderr.write(
                            f"[launch] restarting pod "
                            f"({self.restarts}/{self.max_restarts})\n")
                        for c in self.containers:
                            c.terminate()
                        if self.elastic is not None:
                            self.elastic.reset()
                        for c in self.containers:
                            c.start()
                        continue
                    for c in self.containers:
                        c.terminate()
                    return code
                time.sleep(self.poll_interval)
        finally:
            for c in self.containers:
                c.terminate()

    def stop(self, *_):
        for c in self.containers:
            c.terminate()
        sys.exit(143)


def build_pod(args, extra):
    nnodes = args.nnodes
    nproc = args.nproc_per_node
    world = nnodes * nproc
    master = args.master
    if master is None:
        master = f"127.0.0.1:{_free_port()}"
    host = master.split(":")[0] if nnodes == 1 else socket.gethostname()
    base_port = _free_port()
    all_eps = []
    for node in range(nnodes):
        for p in range(nproc):
            # endpoints are informational on TPU (the coordination service is
            # the real rendezvous); keep the reference's env contract anyway
            all_eps.append(f"{host}:{base_port + node * nproc + p}")
    os.makedirs(args.log_dir, exist_ok=True)
    containers = []
    for p in range(nproc):
        rank = args.node_rank * nproc + p
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(all_eps),
            "PADDLE_CURRENT_ENDPOINT": all_eps[rank],
            "PADDLE_MASTER": master,
            "PADDLE_LOCAL_RANK": str(p),
            "PADDLE_NNODES": str(nnodes),
            "FLAGS_selected_tpus": str(p),
        })
        if args.backend:
            env["JAX_PLATFORMS"] = args.backend
        if args.elastic_timeout:
            env["PADDLE_HEARTBEAT_FILE"] = os.path.join(
                args.log_dir, f"heartbeat.{rank}")
        cmd = [sys.executable, "-u"] + extra
        log = os.path.join(args.log_dir, f"workerlog.{rank}")
        containers.append(Container(rank, cmd, env, log))
    elastic = None
    if args.elastic_timeout:
        from paddle_tpu.distributed.fleet.elastic import ElasticManager
        elastic = ElasticManager(args.log_dir, world,
                                 timeout=args.elastic_timeout)
    return Pod(containers, max_restarts=args.max_restarts, elastic=elastic)


def launch(argv=None):
    parser = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="multi-process training launcher (ref launch/main.py)")
    parser.add_argument("--nnodes", type=int,
                        default=int(os.environ.get("PADDLE_NNODES", 1)))
    parser.add_argument("--node_rank", type=int,
                        default=int(os.environ.get("PADDLE_NODE_RANK", 0)))
    parser.add_argument("--nproc_per_node", type=int, default=1)
    parser.add_argument("--master", default=os.environ.get("PADDLE_MASTER"))
    parser.add_argument("--log_dir", default="log")
    parser.add_argument("--max_restarts", type=int, default=0)
    parser.add_argument("--backend", default=None,
                        help="force JAX_PLATFORMS for workers (e.g. cpu for "
                             "multi-process simulation on one host)")
    parser.add_argument("--elastic_timeout", type=float, default=0,
                        help="heartbeat staleness (seconds) after which a "
                             "hung worker counts as failed (0 = off); "
                             "restarts follow --max_restarts")
    # split at the first non-flag token (the script): everything after belongs
    # to the training script — parse_known_args would otherwise steal flags
    # like `--backend` the user meant for their script
    argv = list(sys.argv[1:] if argv is None else argv)
    split = next((i for i, a in enumerate(argv)
                  if not a.startswith("-") and (
                      i == 0 or argv[i - 1] not in (
                          "--nnodes", "--node_rank", "--nproc_per_node",
                          "--master", "--log_dir", "--max_restarts",
                          "--backend", "--elastic_timeout"))), len(argv))
    args = parser.parse_args(argv[:split])
    extra = argv[split:]
    if not extra:
        parser.error("no training script given")
    if args.nnodes > 1 and args.master is None:
        parser.error("--master host:port is required when nnodes > 1 "
                     "(every node must rendezvous at the same coordinator)")
    pod = build_pod(args, extra)
    signal.signal(signal.SIGTERM, pod.stop)
    signal.signal(signal.SIGINT, pod.stop)
    rc = pod.run()
    sys.exit(rc)
