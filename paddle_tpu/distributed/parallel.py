"""Process/env bringup (ref: `python/paddle/distributed/parallel.py:100`
init_parallel_env — TCPStore + ProcessGroupNCCL + global Group + barrier).

TPU-native: `jax.distributed.initialize` joins the multi-controller JAX cluster
(its coordination service plays the TCPStore role); afterwards every process sees
the global device set and collectives compile into programs. Single-process
multi-device needs no init at all.
"""
from __future__ import annotations

import os

import jax
import numpy as np


_initialized = False


class ParallelEnv:
    """ref: `python/paddle/fluid/dygraph/parallel.py` ParallelEnv."""

    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID",
                                        os.environ.get("RANK", "0")))
        self._world_size = int(os.environ.get(
            "PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", "1")))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._endpoints = eps.split(",") if eps else []
        self._current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def rank(self):
        return self._rank

    @property
    def local_rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def nranks(self):
        return self._world_size

    @property
    def dev_id(self):
        return int(os.environ.get("FLAGS_selected_tpus", "0"))

    @property
    def trainer_endpoints(self):
        return self._endpoints

    @property
    def current_endpoint(self):
        return self._current_endpoint


def init_parallel_env():
    """Join the cluster. Multi-process: jax.distributed.initialize using the
    launch env (coordinator = PADDLE_MASTER / first endpoint). Single-process:
    no-op — all local devices are already visible."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    env = ParallelEnv()
    # NOTE: jax.process_count() would initialise the XLA backend, after which
    # jax.distributed.initialize refuses to run — consult the distributed
    # client state instead
    from paddle_tpu.framework.jax_compat import distributed_is_initialized
    already_joined = distributed_is_initialized()
    if env.world_size > 1 and not already_joined:
        coordinator = os.environ.get("PADDLE_MASTER") or (
            env.trainer_endpoints[0] if env.trainer_endpoints else None)
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=env.world_size,
            process_id=env.rank,
        )
    hb = os.environ.get("PADDLE_HEARTBEAT_FILE")
    if hb:
        from paddle_tpu.distributed.fleet.elastic import start_heartbeat
        start_heartbeat(hb)
    _initialized = True
    return env


def is_initialized():
    return _initialized or jax.process_count() > 1


def get_rank(group=None):
    if group is not None:
        return group.get_group_rank(jax.process_index())
    return int(os.environ.get("PADDLE_TRAINER_ID", jax.process_index()))


def get_world_size(group=None):
    if group is not None:
        return group.world_size
    ws = os.environ.get("PADDLE_TRAINERS_NUM")
    if ws is not None:
        return int(ws)
    return jax.process_count()


def barrier(group=None):
    """Block until all processes arrive (compiled psum over one scalar)."""
    if jax.process_count() == 1:
        import jax.numpy as jnp
        jnp.zeros(()).block_until_ready()
        return
    from paddle_tpu.distributed import liveness
    if liveness.current() is not None:
        # liveness-guarded fleet (elastic training): the polling barrier
        # converts a dead peer into typed PeerLost instead of wedging in
        # wait_at_barrier (whose expiry this jaxlib cannot survive)
        from paddle_tpu.distributed.collective import _kv_client
        _barrier_seq[0] += 1
        client = _kv_client()
        liveness.kv_barrier(client, f"pbar/{_barrier_seq[0]}",
                            rank=get_rank(), world=jax.process_count(),
                            timeout_ms=60_000)
        if get_rank() == 0 and _barrier_seq[0] >= 3:
            # two-generations-back sweep (same deferral contract as the
            # allgather barriers): seq N completing proves everyone is
            # fully past seq N-2's listing loop
            liveness.kv_barrier_cleanup(client,
                                        f"pbar/{_barrier_seq[0] - 2}")
        return
    from jax.experimental import multihost_utils
    try:
        multihost_utils.sync_global_devices("paddle_tpu_barrier")
    except Exception:  # noqa: BLE001 — backend can't run multiprocess XLA
        # coordination-service barrier: same rendezvous, no compiled program
        # (0.4.x CPU jaxlib cannot compile cross-process computations); the
        # id advances in lockstep because every rank calls barrier() in the
        # same program order
        from paddle_tpu.distributed.collective import _kv_client
        _barrier_seq[0] += 1
        _kv_client().wait_at_barrier(f"ptpu_barrier/{_barrier_seq[0]}",
                                     60_000)


_barrier_seq = [0]
