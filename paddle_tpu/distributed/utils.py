"""``paddle.distributed.utils`` — MoE dispatch primitives.

Rebuild of the reference's `python/paddle/distributed/utils/moe_utils.py`
(`global_scatter` :25, `global_gather` :145) over
`operators/collective/global_scatter_op.cc:80`: rows grouped by
(expert, destination rank) are exchanged all-to-all so each rank ends up
holding the rows destined for its local experts.

Count layout (reference contract): ``local_count[e * world + r]`` = number of
my rows headed to expert ``e`` living on rank ``r``; ``global_count`` is the
transpose view (how many I receive). In-graph MoE should use
`incubate.moe.MoELayer` (static-shape einsum dispatch compiled by GSPMD); these
eager functions are the correctness/interop path, like the reference's eager
ProcessGroup calls.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.common import ensure_tensor

__all__ = ["global_scatter", "global_gather"]


def _counts(t):
    return np.asarray(ensure_tensor(t).numpy()).astype(np.int64).reshape(-1)


def _world(group):
    from paddle_tpu.distributed.parallel import get_world_size
    if group is not None and set(group.ranks) != set(range(get_world_size())):
        # the allgather emulation is a whole-world collective; a subgroup
        # would read other ranks' buffers and desync ranks outside the group
        raise NotImplementedError(
            "global_scatter/global_gather support the default (world) group "
            "only on the eager path; in-graph MoE dispatch over a mesh axis "
            "lives in incubate.moe.MoELayer")
    return max(get_world_size(), 1)


def _rank():
    from paddle_tpu.distributed.parallel import get_rank
    return get_rank()


def global_scatter(x, local_count, global_count, group=None, use_calc_stream=True):
    """Send my rows (grouped by expert-major (e, dest-rank) segments per
    ``local_count``) to their destination ranks; receive the rows my experts
    serve, ordered (src-rank, expert) to match ``global_count``
    (ref moe_utils.global_scatter :25)."""
    x = ensure_tensor(x)
    lc = _counts(local_count)
    gc = _counts(global_count)
    world = _world(group)
    n_expert = lc.size // world
    if world == 1:
        # single rank: receive order (src-rank-major) == send order reshuffled
        # from expert-major; with one rank both collapse to expert order
        return Tensor(x._data, _internal=True)

    from paddle_tpu.distributed.collective import _proc_allgather
    # variable-size exchange via the allgather emulation path (correctness):
    # everyone shares rows + counts, each rank slices out its inbox
    all_counts = _proc_allgather(
        jnp.asarray(lc))                       # [world, n_expert*world]
    n_rows = np.asarray(_proc_allgather(
        jnp.asarray([x.shape[0]], np.int64))).reshape(-1)
    pad = int(n_rows.max())
    xp = jnp.zeros((pad,) + tuple(x.shape[1:]), x._data.dtype)
    xp = xp.at[:x.shape[0]].set(x._data)
    all_rows = np.asarray(_proc_allgather(xp))
    me = _rank()
    counts_np = np.asarray(all_counts)
    # reference contract check: my global_count must be the transpose view of
    # everyone's local_count (gc[e*world+src] == lc_src[e*world+me])
    expect_gc = np.asarray([counts_np[src][e * world + me]
                            for e in range(n_expert) for src in range(world)])
    got_gc = gc.reshape(n_expert, world).reshape(-1)
    if not np.array_equal(np.sort(expect_gc), np.sort(got_gc)) and \
            not np.array_equal(
                expect_gc.reshape(n_expert, world),
                gc.reshape(n_expert, world)):
        raise ValueError(
            "global_count is not the transpose of the gathered local_counts")
    out = []
    # receive order: src-rank-major, expert within (matches global_count's
    # [e * world + r] read on the receiver with r = src)
    for src in range(world):
        offs = np.zeros(1 + counts_np.shape[1], np.int64)
        np.cumsum(counts_np[src], out=offs[1:])
        for e in range(n_expert):
            seg = e * world + me
            a, b = int(offs[seg]), int(offs[seg + 1])
            if b > a:
                out.append(all_rows[src][a:b])
    if out:
        res = np.concatenate(out, axis=0)
    else:
        res = np.zeros((0,) + tuple(x.shape[1:]), np.asarray(all_rows).dtype)
    return Tensor(jnp.asarray(res), _internal=True)


def global_gather(x, local_count, global_count, group=None, use_calc_stream=True):
    """Inverse of :func:`global_scatter`: return the rows I originally sent,
    back in my local expert-major order (ref moe_utils.global_gather :145)."""
    x = ensure_tensor(x)
    lc = _counts(local_count)
    gc = _counts(global_count)
    world = _world(group)
    n_expert = lc.size // world
    if world == 1:
        return Tensor(x._data, _internal=True)

    from paddle_tpu.distributed.collective import _proc_allgather
    n_rows = np.asarray(_proc_allgather(
        jnp.asarray([x.shape[0]], np.int64))).reshape(-1)
    pad = int(n_rows.max())
    xp = jnp.zeros((pad,) + tuple(x.shape[1:]), x._data.dtype)
    xp = xp.at[:x.shape[0]].set(x._data)
    all_rows = np.asarray(_proc_allgather(xp))
    all_gc = np.asarray(_proc_allgather(jnp.asarray(gc)))
    me = _rank()
    # On each holder rank, rows sit in (src-rank, expert) order; to reclaim my
    # rows IN MY SEND ORDER (expert-major across dest ranks) walk my
    # local_count segments and pull from the holder's buffer
    # per-holder cumulative offsets over its (src-rank-major, expert) inbox
    # order: seg_counts[dst][src, e] = rows dst received from src for expert e
    seg_counts = all_gc.reshape(world, n_expert, world).transpose(0, 2, 1)
    seg_offsets = np.zeros((world, world * n_expert + 1), np.int64)
    np.cumsum(seg_counts.reshape(world, -1), axis=1, out=seg_offsets[:, 1:])
    out = []
    for e in range(n_expert):
        for dst in range(world):
            cnt = int(lc[e * world + dst])
            if cnt == 0:
                continue
            off = int(seg_offsets[dst][me * n_expert + e])
            out.append(all_rows[dst][off:off + cnt])
    if out:
        res = np.concatenate(out, axis=0)
    else:
        res = np.zeros((0,) + tuple(x.shape[1:]), np.asarray(all_rows).dtype)
    return Tensor(jnp.asarray(res), _internal=True)
