"""DataParallel wrapper (ref: `python/paddle/fluid/dygraph/parallel.py:457` +
EagerReducer `paddle/fluid/distributed/collective/reducer.cc:89`).

TPU-native: there is no reducer. Wrapping a Layer in DataParallel marks the batch
dimension of its inputs as sharded over the 'dp' mesh axis; under a captured train
step GSPMD partitions the graph and inserts the gradient psum automatically —
overlapped with backward by XLA's scheduler, which is exactly what
MarkVarReady/FusedAllReduceSchedule (:769/:1033) hand-build in the reference.
Eager single-process multi-device runs the same way through jit.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer import Layer
from paddle_tpu.distributed.mesh import get_mesh, auto_mesh


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        mesh = get_mesh()
        if mesh is None and len(jax.devices()) > 1:
            mesh = auto_mesh(dp=len(jax.devices()))
        self._mesh = mesh
        if self._mesh is not None and "dp" in self._mesh.axis_names:
            # params replicated across dp (ref: param broadcast at init,
            # `parallel.py` sync_params_buffers)
            repl = NamedSharding(self._mesh, PartitionSpec())
            for p in layers.parameters():
                if not isinstance(p._data, jax.core.Tracer):
                    p._write(jax.device_put(p._data, repl))

    def _shard_input(self, x):
        if self._mesh is None or "dp" not in self._mesh.axis_names:
            return x
        if not isinstance(x, Tensor):
            return x
        spec = PartitionSpec("dp", *([None] * (x.ndim - 1)))
        sharding = NamedSharding(self._mesh, spec)
        if isinstance(x._data, jax.core.Tracer):
            arr = jax.lax.with_sharding_constraint(x._data, sharding)
        else:
            arr = jax.device_put(x._data, sharding)
        t = Tensor(arr, stop_gradient=x.stop_gradient, _internal=True)
        t._grad_node = x._grad_node
        t._out_slot = x._out_slot
        return t

    def forward(self, *inputs, **kwargs):
        inputs = tuple(self._shard_input(x) for x in inputs)
        kwargs = {k: self._shard_input(v) for k, v in kwargs.items()}
        return self._layers(*inputs, **kwargs)

    # pass-throughs so DataParallel is a drop-in (ref parallel.py)
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    @property
    def parameters_(self):
        return self._layers.parameters()
