"""ZeRO-style sharding (ref: `python/paddle/distributed/sharding/group_sharded.py:54`
group_sharded_parallel + GroupSharded stages 2/3 under meta_parallel/sharding/).

TPU-native: stage 1/2 = optimizer-state (and grad) arrays laid out sharded over the
'dp'/'sdp' mesh axis; stage 3 = parameters themselves sharded, with XLA's SPMD
partitioner materializing the all-gathers the reference hand-codes as forward hooks
(`group_sharded_stage3.py:185`). Under a captured train step this is pure sharding
annotation — ~50 lines vs the reference's ~2.5k.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.mesh import get_mesh, auto_mesh


def _shard_spec_for(shape, mesh, axis):
    """Shard the largest dim divisible by the axis size; replicate otherwise."""
    size = mesh.shape[axis]
    dims = sorted(range(len(shape)), key=lambda i: -shape[i])
    for d in dims:
        if shape[d] % size == 0 and shape[d] >= size:
            spec = [None] * len(shape)
            spec[d] = axis
            return PartitionSpec(*spec)
    return PartitionSpec()


def _place(t: Tensor, sharding):
    if not isinstance(t._data, jax.core.Tracer):
        t._write(jax.device_put(t._data, sharding))


def zero1_partition_spec(shape, mesh, axis="dp", base_spec=None):
    """ZeRO-1 placement for ONE optimizer-state leaf (arxiv 2004.13336:
    shard the weight-update/optimizer-state over the data-parallel axis).

    Picks the LARGEST dim that the param's own sharding (``base_spec`` —
    its mp/sp placement, which moments must mirror) leaves unsharded and
    that divides by the axis size, and assigns ``axis`` to it, so an
    mp-sharded weight gets dp x mp - sharded moments. Returns None when no
    dim qualifies or the axis has size 1 (replicate: nothing to win)."""
    size = mesh.shape.get(axis, 1) if mesh is not None else 1
    if size <= 1 or not shape:
        return None
    base = list(base_spec) if base_spec is not None else []
    base = base + [None] * (len(shape) - len(base))
    cands = [d for d in range(len(shape))
             if base[d] is None and shape[d] % size == 0 and shape[d] >= size]
    if not cands:
        return None
    d = max(cands, key=lambda i: shape[i])
    base[d] = axis
    return PartitionSpec(*base)


def shard_optimizer_states(optimizer, mesh=None, axis="dp"):
    """Stage-1/2: lay optimizer accumulators out sharded over the data axis."""
    mesh = mesh or get_mesh()
    if mesh is None:
        return optimizer
    orig_accumulator = optimizer._accumulator

    def sharded_accumulator(name, p, init=None, dtype=None):
        t = orig_accumulator(name, p, init=init, dtype=dtype)
        spec = _shard_spec_for(tuple(t._data.shape), mesh, axis)
        _place(t, NamedSharding(mesh, spec))
        return t

    optimizer._accumulator = sharded_accumulator
    return optimizer


def shard_parameters(model, mesh=None, axis="dp"):
    """Stage-3: shard the parameter arrays themselves. Parameters that
    already carry a named mesh sharding (a pipeline's 'pp'-stacked stage
    params, an mpu layer's 'mp' shard) are left in place — stage3 composes
    with model parallelism by sharding the REMAINING (replicated) params
    over the data axis, not by fighting placements the model chose."""
    mesh = mesh or get_mesh()
    if mesh is None:
        return model
    for p in model.parameters():
        sh = getattr(p._data, "sharding", None)
        if isinstance(sh, NamedSharding) and any(
                s is not None for s in sh.spec):
            continue
        spec = _shard_spec_for(tuple(p._data.shape), mesh, axis)
        _place(p, NamedSharding(mesh, spec))
    return model


def _host_device_shardings(shape, mesh, axis):
    """(host, device) sharding pair for one state array. On backends with no
    distinct host tier (CPU: only ``unpinned_host``) the host sharding IS the
    device sharding — offload degrades to a no-op instead of a PJRT error,
    so the CPU dryrun/gate can still check stage-3 numerics."""
    from paddle_tpu.framework.jax_compat import host_memory_kind
    if mesh is not None:
        kind = host_memory_kind(mesh.devices.flat)
        spec = _shard_spec_for(shape, mesh, axis)
        host = (NamedSharding(mesh, spec, memory_kind=kind) if kind
                else NamedSharding(mesh, spec))
        return host, NamedSharding(mesh, spec)
    dev = jax.devices()[0]
    kind = host_memory_kind([dev])
    host = (jax.sharding.SingleDeviceSharding(dev, memory_kind=kind) if kind
            else jax.sharding.SingleDeviceSharding(dev))
    return host, jax.sharding.SingleDeviceSharding(dev)


def _flag_offload(t, mesh, axis):
    host, devsh = _host_device_shardings(tuple(t._data.shape), mesh, axis)
    t._offload_host = host
    t._offload_device = devsh
    return t


def offload_optimizer_states(optimizer, mesh=None, axis="dp"):
    """Stage-3 host offload (ref `group_sharded_stage3.py:61` offload=True,
    `_param2buffer` :133): optimizer accumulators, fused flat state buffers
    and fp32 master weights RESIDE in host memory (``pinned_host``) between
    steps. The step runner fetches them to device memory for the update and
    pushes the new values home afterwards — donate+fetch, so HBM holds
    optimizer state only transiently during the step. The compiled program
    itself stays memory-kind-free (portable across backends; the transfers
    happen at the call boundary, see jit/static_function.py)."""
    mesh = mesh or get_mesh()
    if getattr(optimizer, "_offloaded_states", None) is not None:
        return optimizer
    optimizer._offloaded_states = []

    def collect():
        """The CURRENT state tensors — recomputed every step so that
        set_state_dict (which rebinds whole accumulator dicts) and fused
        freeze/unfreeze rebuilds self-heal instead of leaving stale entries
        shuttling dead arrays (round-3 review finding)."""
        out = []
        for store in optimizer._accumulators.values():
            out.extend(store.values())
        out.extend(optimizer._master_weights.values())
        for meta in getattr(optimizer, "_fused_parts", {}).values():
            out.extend(meta["states"])
        for t in out:
            if not hasattr(t, "_offload_host"):
                _flag_offload(t, mesh, axis)
        optimizer._offloaded_states = out
        return out

    orig_step = optimizer.step

    def step():
        # eager fetch: concrete host-resident state moves to device before
        # the update math touches it (inside a capture probe the arrays are
        # concrete at entry too, so the probe never sees host avals)
        for t in collect():
            d = t._data
            if not isinstance(d, jax.core.Tracer) and \
                    getattr(d.sharding, "memory_kind", None) == "pinned_host":
                t._data = jax.device_put(d, t._offload_device)
        out = orig_step()
        # eager push-back over the post-step state set (lazy creation happens
        # inside the step); during capture the new values are tracers and the
        # compiled-step runner does the push-back instead
        for t in collect():
            d = t._data
            if not isinstance(d, jax.core.Tracer) and \
                    getattr(d.sharding, "memory_kind", None) != "pinned_host":
                t._data = jax.device_put(d, t._offload_host)
        return out

    optimizer.step = step
    return optimizer


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2**23,
                           segment_size=2**20, sync_comm=False,
                           dp_group=None, exclude_layer=None):
    """ref signature: `distributed/sharding/group_sharded.py:54`.
    level: 'os' (stage1), 'os_g' (stage2), 'p_g_os' (stage3).
    ``offload=True`` additionally homes optimizer state in host memory
    (works on a single device too, like the reference's CPU offload)."""
    mesh = get_mesh()
    if mesh is None and len(jax.devices()) > 1:
        mesh = auto_mesh(dp=len(jax.devices()))
    if mesh is not None:
        if level in ("os", "os_g", "p_g_os"):
            shard_optimizer_states(optimizer, mesh)
        if level == "p_g_os":
            shard_parameters(model, mesh)
    if offload:
        offload_optimizer_states(optimizer, mesh)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """ref: `group_sharded.py:222` — gather shards and save one logical ckpt.
    Global arrays already hold the full logical value, so plain save works."""
    import os
    from paddle_tpu.framework import io as fio
    os.makedirs(output, exist_ok=True) if not output.endswith(".pdparams") else None
    base = output if not os.path.isdir(output) else os.path.join(output, "model")
    fio.save(model.state_dict(), base + ".pdparams")
    if optimizer is not None:
        fio.save(optimizer.state_dict(), base + ".pdopt")
