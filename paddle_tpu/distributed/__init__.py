"""paddle.distributed — TPU-native distributed stack.

Design (SURVEY.md §2.6/§2.7): the reference's NCCL ProcessGroups + c_* collective ops
+ fleet meta-optimizers collapse onto ONE mechanism — a `jax.sharding.Mesh` with
collectives compiled by XLA over ICI/DCN. `init_parallel_env` ≈
`jax.distributed.initialize` (coordination service ≈ TCPStore,
`paddle/fluid/distributed/store/tcp_store.h:117`). The eager collective API operates
on globally-sharded arrays via shard_map so `paddle.distributed.all_reduce(...)`
keeps its signature while compiling to one XLA collective.
"""
from paddle_tpu.distributed.parallel import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, is_initialized, barrier,
    ParallelEnv,
)
from paddle_tpu.distributed.collective import (  # noqa: F401
    all_reduce, all_gather, all_gather_object, reduce, broadcast, scatter,
    reduce_scatter, alltoall, alltoall_single, send, recv, isend, irecv,
    new_group, get_group, wait, ReduceOp, Group, split_group, destroy_process_group,
)
from paddle_tpu.distributed.mesh import (  # noqa: F401
    ProcessMesh, get_mesh, set_mesh, auto_mesh, shard_tensor, shard_op,
    default_mesh_axes,
)
from paddle_tpu.distributed import fleet  # noqa: F401
from paddle_tpu.distributed.parallel_wrappers import DataParallel  # noqa: F401
from paddle_tpu.distributed import sharding  # noqa: F401
from paddle_tpu.distributed.spawn import spawn  # noqa: F401
from paddle_tpu.distributed.checkpoint import (  # noqa: F401
    save_sharded, load_sharded, async_save)
from paddle_tpu.distributed import auto_parallel  # noqa: F401
from paddle_tpu.distributed import rpc  # noqa: F401
from paddle_tpu.distributed import utils  # noqa: F401
from paddle_tpu.distributed.utils import global_scatter, global_gather  # noqa: F401
