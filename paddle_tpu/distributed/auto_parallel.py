"""Auto-parallel Engine.

Counterpart of the reference's semi-automatic SPMD planner
(`python/paddle/distributed/auto_parallel/engine.py:59` — `_build` :514,
`_plan` :669, `_parallel` :697, `fit` :802; completion `completion.py:147`,
partitioning `partitioner.py:38`, comm insertion `reshard.py:1009`).

TPU-native collapse: GSPMD IS the completer/partitioner/resharder — user
annotations (`shard_tensor`, the mpu layers' param shardings) seed the
propagation and XLA inserts the collectives. What remains framework work, and
lives here, is the Engine UX: build the mesh from a strategy, place inputs,
capture the train/eval/predict step once, and run the loops. The planner's
cost-model role shrinks to `plan()`: pick a mesh factorization for the
device count with a simple capacity heuristic (the reference's Planner
searches dist-attr space; under GSPMD only the mesh shape is left to choose).
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec

import paddle_tpu as paddle
from paddle_tpu.distributed.mesh import auto_mesh, get_mesh, set_mesh


class Strategy:
    """ref `auto_parallel/strategy.py` — knobs the plan consumes."""

    def __init__(self):
        self.auto_mode = "semi"
        self.dp = None            # None = infer
        self.mp = 1
        self.pp = 1
        self.sp = 1
        self.amp = type("amp", (), {"enable": False, "level": "O2",
                                    "dtype": "bfloat16"})()
        self.recompute = type("rc", (), {"enable": False})()
        # gradient-accumulation microbatching (ref strategy.gradient_merge):
        # k_steps loader batches fold into ONE optimizer apply inside the
        # fused scanned step (grads accumulate in f32 on device)
        self.gradient_merge = type("gm", (), {"enable": False,
                                              "k_steps": 1})()
        # ZeRO-1 (ref strategy.sharding stage 1): optimizer state sharded
        # over the dp axis inside the captured step. enable=False still
        # AUTO-shards when the mesh has dp > 1; set stage=0 to force off.
        self.sharding = type("sh", (), {"enable": False, "stage": 1})()
        # scanned-layer-stack fused train step: "auto" routes GPT models
        # through paddle_tpu.train.ScanTrainStep (O(1)-in-depth compile,
        # donated buffers); False always uses the unrolled capture
        self.fused_scan = "auto"


def estimate_step_cost(n_params, dp, mp, n_layers=None, hidden=None,
                       batch_tokens=None, bytes_per_param=2,
                       hbm_bytes=16e9, optimizer_state_mult=7.0):
    """Per-step communication bytes + memory feasibility for a (dp, mp) split
    — the quantitative core the reference spreads across
    `auto_parallel/cost/{comm_op_cost,tensor_cost,estimate_cost}.py`.

    - DP grad sync: ring all-reduce moves 2*(dp-1)/dp * M bytes per device.
    - TP activation sync: Megatron inserts 2 all-reduces per layer in fwd and
      2 in bwd, each of the full activation [batch_tokens, hidden].
    - memory: params + grads + master/adam states, sharded over mp only
      (dp replicates; ZeRO would divide further — the planner is conservative).

    Returns (comm_bytes, fits_memory).
    """
    m_bytes = n_params * bytes_per_param
    dp_comm = 2.0 * (dp - 1) / max(dp, 1) * m_bytes
    tp_comm = 0.0
    if mp > 1 and n_layers and hidden and batch_tokens:
        act = batch_tokens * hidden * bytes_per_param
        tp_comm = 4.0 * n_layers * 2.0 * (mp - 1) / mp * act
    state_bytes = n_params * (bytes_per_param + optimizer_state_mult * 4) / mp
    return dp_comm + tp_comm, state_bytes <= hbm_bytes


def plan_mesh(n_devices, strategy=None, n_params=None, n_layers=None,
              hidden=None, batch_tokens=None, hbm_bytes=16e9):
    """Pick (dp, mp, sp) for the device count (ref Planner,
    `auto_parallel/planner_v2.py` + cost model): honor user-pinned axes, then
    enumerate divisor splits of the remainder and take the memory-feasible
    split with the least estimated communication. Without model stats the
    tie-break prefers pure dp (cheapest on ICI), trading dp for mp only when
    the parameter+state footprint cannot fit one device's HBM."""
    s = strategy or Strategy()
    mp_pinned = int(s.mp or 1) if s.mp and s.mp > 1 else None
    sp = int(s.sp or 1)
    if s.dp is not None:
        mp = mp_pinned or 1
        if s.dp * mp * sp != n_devices:
            raise ValueError("dp x mp x sp != device count")
        return dict(dp=s.dp, mp=mp, sp=sp)
    rest = n_devices // sp
    if rest * sp != n_devices:
        raise ValueError(f"sp({sp}) does not divide device count {n_devices}")
    candidates = []
    for mp in ([mp_pinned] if mp_pinned else
               [d for d in range(1, rest + 1) if rest % d == 0]):
        dp = rest // mp
        if dp * mp != rest:
            continue
        have_stats = bool(n_params and n_layers and hidden and batch_tokens)
        if n_params:
            comm, fits = estimate_step_cost(
                n_params, dp, mp, n_layers=n_layers, hidden=hidden,
                batch_tokens=batch_tokens, hbm_bytes=hbm_bytes)
        else:
            comm, fits = float(mp), True
        if not fits:
            # nothing ideal: prefer the split closest to fitting (largest mp)
            key = (1, -mp, comm)
        elif have_stats:
            key = (0, comm, mp)
        else:
            # without activation stats the TP comm term is unknowable —
            # be conservative: smallest mp that fits memory wins
            key = (0, mp, comm)
        candidates.append((key, mp, dp))
    if not candidates:
        raise ValueError(
            f"mp({mp_pinned}) x sp({sp}) does not divide {n_devices}")
    _, mp, dp = min(candidates)
    return dict(dp=dp, mp=mp, sp=sp)


class Engine:
    """ref `auto_parallel/engine.py:59`. Wraps (model, loss, optimizer) and
    runs captured SPMD train/eval/predict steps over the planned mesh."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics or []
        self._strategy = strategy or Strategy()
        self._mesh = None
        self._train_step = None
        self._eval_step = None
        self._scan_step = None     # ScanTrainStep when the fused route took
        self._history = []

    # ------------------------------------------------------------------ plan

    def prepare(self, mesh=None):
        """_build + _plan + _parallel: install the mesh and capture steps."""
        if mesh is not None:
            self._mesh = mesh
            set_mesh(mesh)
        elif get_mesh() is not None:
            self._mesh = get_mesh()
        else:
            n_params = sum(int(np.prod(p.shape))
                           for p in self._model.parameters())
            shape = plan_mesh(len(jax.devices()), self._strategy, n_params)
            self._mesh = auto_mesh(**shape)
        model, loss, opt = self._model, self._loss, self._optimizer

        if self._try_scan_capture():
            # fused scanned train step captured; train_step stays None and
            # fit() routes through self._scan_step
            pass
        else:
            @paddle.jit.to_static
            def train_step(x, y):
                out = model(x)
                l = loss(out, y)
                l.backward()
                opt.step()
                opt.clear_grad()
                return l

            self._train_step = train_step

        if loss is None and self._scan_step is not None:
            # fused GPT route without an external loss fn: eval on the
            # model's OWN causal-LM loss (the objective the step trains)
            @paddle.jit.to_static
            def eval_step(x, y):
                _, l = model(x, labels=y)
                return l
        else:
            @paddle.jit.to_static
            def eval_step(x, y):
                out = model(x)
                return loss(out, y)

        self._eval_step = eval_step
        return self

    def _try_scan_capture(self):
        """Route GPT models through the scan-over-layers donated train step
        (paddle_tpu.train.ScanTrainStep): O(1)-in-depth compile, gradient
        merge microbatching, ZeRO-1 over dp. Falls back to the unrolled
        to_static capture whenever the (model, loss, optimizer) trio is
        outside the fused step's envelope."""
        s = self._strategy
        if getattr(s, "fused_scan", "auto") is False or \
                self._optimizer is None:
            return False
        from paddle_tpu.models.gpt import GPTForCausalLM
        import paddle_tpu.nn as nn
        if not isinstance(self._model, GPTForCausalLM):
            return False
        # the fused step computes the model's own causal-LM CE (plain mean
        # token CE); only take the route when the engine loss IS that exact
        # function — a default-configured CrossEntropyLoss. Any non-default
        # knob (class weights, reduction, smoothing, custom ignore_index)
        # would be silently dropped, so those fall back to the unrolled
        # capture. ignore_index=-100 is inert for valid token ids.
        if self._loss is not None:
            l = self._loss
            if not isinstance(l, nn.CrossEntropyLoss):
                return False
            if (l.weight is not None or l.reduction != "mean"
                    or l.soft_label or l.label_smoothing
                    or not l.use_softmax or l.axis != -1
                    or l.ignore_index != -100):
                return False
        gm = getattr(s, "gradient_merge", None)
        k = int(getattr(gm, "k_steps", 1) or 1) if gm is not None and \
            getattr(gm, "enable", False) else 1
        sh = getattr(s, "sharding", None)
        if sh is not None and getattr(sh, "enable", False):
            zero1 = getattr(sh, "stage", 1) >= 1
        elif sh is not None and getattr(sh, "stage", 1) == 0:
            zero1 = False
        else:
            zero1 = "auto"
        try:
            from paddle_tpu.train import ScanTrainStep, ScanUnsupported
        except ImportError:
            return False
        try:
            self._scan_step = ScanTrainStep(
                self._model, self._optimizer, microbatches=k, zero1=zero1,
                mesh=self._mesh)
        except ScanUnsupported:
            return False
        from paddle_tpu.observability import metrics
        metrics.counter("train.scan_route").inc()
        return True

    @property
    def train_step_kind(self):
        return "scan" if self._scan_step is not None else "unrolled"

    def _sync_scan(self):
        if self._scan_step is not None and self._scan_step.dirty:
            self._scan_step.sync_to_model()

    def _place(self, arr):
        a = arr._data if hasattr(arr, "_data") else np.asarray(arr)
        if self._mesh is not None and "dp" in self._mesh.axis_names \
                and a.shape and a.shape[0] % self._mesh.shape["dp"] == 0:
            a = jax.device_put(a, NamedSharding(
                self._mesh, PartitionSpec(
                    "dp", *([None] * (len(a.shape) - 1)))))
        return paddle.Tensor(a, _internal=True)

    # ------------------------------------------------------------------ loops

    def fit(self, train_data, epochs=1, steps_per_epoch=None, log_freq=10,
            valid_data=None):
        if self._train_step is None and self._scan_step is None:
            self.prepare()
        history = []
        # gradient merge: k_steps LOADER batches fold into one optimizer
        # apply (the reference strategy semantics) — buffer, concatenate,
        # and let the fused step scan over them as microbatches
        merge_k = self._scan_step.microbatches if self._scan_step else 1
        for epoch in range(epochs):
            losses = []
            buf = []
            for step, batch in enumerate(train_data):
                if steps_per_epoch is not None and step >= steps_per_epoch:
                    break
                x, y = batch[0], batch[1]
                if self._scan_step is not None:
                    buf.append((self._place(x), self._place(y)))
                    if len(buf) == merge_k:
                        losses.append(self._apply_scan(buf))
                        buf = []
                else:
                    l = self._train_step(self._place(x), self._place(y))
                    losses.append(float(l))
            if buf:
                # partial accumulation group at epoch end
                losses.append(self._apply_scan(buf))
            entry = {"epoch": epoch, "loss": float(np.mean(losses))}
            if valid_data is not None:
                entry["val_loss"] = self.evaluate(valid_data)["loss"]
            history.append(entry)
        self._sync_scan()    # model/optimizer state_dict see trained values
        self._history = history
        return history

    def _apply_scan(self, buf):
        """One fused step over the buffered (x, y) loader batches. Equal
        batch sizes scan as microbatches; a ragged group (short final
        loader batch) runs as ONE microbatch — still a single optimizer
        apply over all its tokens."""
        import jax.numpy as jnp
        xs = jnp.concatenate([x._data for x, _ in buf])
        ys = jnp.concatenate([y._data for _, y in buf])
        sizes = {x._data.shape[0] for x, _ in buf}
        m = len(buf) if len(sizes) == 1 else 1
        return self._scan_step.step(xs, ys, microbatches=m)

    def evaluate(self, eval_data, steps=None):
        if self._eval_step is None:
            self.prepare()
        self._sync_scan()
        losses = []
        for step, batch in enumerate(eval_data):
            if steps is not None and step >= steps:
                break
            x, y = batch[0], batch[1]
            losses.append(float(self._eval_step(self._place(x),
                                                self._place(y))))
        return {"loss": float(np.mean(losses))}

    def predict(self, test_data, steps=None):
        self._sync_scan()
        outs = []
        for step, batch in enumerate(test_data):
            if steps is not None and step >= steps:
                break
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            with paddle.no_grad():
                outs.append(self._model(self._place(x)))
        return outs

    # ------------------------------------------------------------------ ckpt

    def save(self, path):
        self._sync_scan()
        from paddle_tpu.distributed.checkpoint import save_sharded
        save_sharded({"model": self._model.state_dict(),
                      "optimizer": self._optimizer.state_dict()
                      if self._optimizer else {}}, path)

    def load(self, path):
        from paddle_tpu.distributed.checkpoint import load_sharded
        flat = load_sharded(path)
        model_sd = {k[len("model/"):]: v for k, v in flat.items()
                    if k.startswith("model/")}
        self._model.set_state_dict(model_sd)
        if self._optimizer is not None:
            opt_sd = {k[len("optimizer/"):]: v for k, v in flat.items()
                      if k.startswith("optimizer/")}
            if opt_sd:
                self._optimizer.set_state_dict(opt_sd)
        if self._scan_step is not None:
            self._scan_step.refresh_from_model()
        return self
