"""Device mesh management — the substrate for every parallelism strategy.

Ref analog: `CommunicateTopology`/`HybridCommunicateGroup`
(`python/paddle/distributed/fleet/base/topology.py:53,139`) which carve NCCL comm
groups out of a 4-D dp×mp×pp×sharding grid. Here the grid IS a
`jax.sharding.Mesh`; "comm groups" are mesh axes, and collectives ride ICI because
XLA lays them out that way.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from paddle_tpu.core.tensor import Tensor

P = PartitionSpec

_global_mesh: Mesh | None = None

# canonical axis order for hybrid parallelism (outer -> inner, DCN -> ICI)
AXIS_ORDER = ("pp", "dp", "sdp", "ep", "mp", "sp")


def default_mesh_axes():
    return AXIS_ORDER


def set_mesh(mesh: Mesh):
    global _global_mesh
    _global_mesh = mesh
    return mesh


def get_mesh() -> Mesh | None:
    return _global_mesh


def auto_mesh(dp=1, mp=1, pp=1, sp=1, ep=1, sdp=1, devices=None) -> Mesh:
    """Build (and install) a mesh with the canonical hybrid axes, sized so that
    the product covers the device count (dp auto-grows if every axis is 1)."""
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    sizes = {"pp": pp, "dp": dp, "sdp": sdp, "ep": ep, "mp": mp, "sp": sp}
    prod = int(np.prod(list(sizes.values())))
    if prod == 1 and n > 1:
        sizes["dp"] = n
        prod = n
    if prod != n:
        raise ValueError(
            f"mesh axes product {prod} != device count {n}; pass explicit sizes")
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    arr = np.asarray(devs).reshape(shape)
    mesh = Mesh(arr, AXIS_ORDER)
    return set_mesh(mesh)


class ProcessMesh:
    """User-facing mesh annotation (ref: `auto_parallel/process_mesh.py`)."""

    def __init__(self, mesh=None, dim_names=None, shape=None, process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
            self._shape = tuple(arr.shape)
            self._process_ids = arr.reshape(-1).tolist()
        else:
            self._shape = tuple(shape or ())
            self._process_ids = list(process_ids or range(int(np.prod(self._shape))))
        self._dim_names = list(dim_names) if dim_names is not None else [
            f"d{i}" for i in range(len(self._shape))]

    @property
    def shape(self):
        return list(self._shape)

    @property
    def process_ids(self):
        return self._process_ids

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def ndim(self):
        return len(self._shape)

    def to_jax(self) -> Mesh:
        devs = np.asarray(jax.devices())[np.asarray(self._process_ids)]
        return Mesh(devs.reshape(self._shape), tuple(self._dim_names))

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh) and
                self._shape == other._shape and
                self._process_ids == other._process_ids)

    def __repr__(self):
        return (f"ProcessMesh(shape={self._shape}, "
                f"dim_names={self._dim_names})")


def _to_jax_mesh(mesh):
    if isinstance(mesh, ProcessMesh):
        return mesh.to_jax()
    return mesh


def shard_tensor(x, mesh=None, placements=None, process_mesh=None, shard_spec=None):
    """Place a Tensor with a NamedSharding (ref: `auto_parallel/interface.py`
    shard_tensor annotations; here it's a physical device_put or an in-graph
    sharding constraint)."""
    mesh = _to_jax_mesh(mesh if mesh is not None else
                        (process_mesh if process_mesh is not None
                         else get_mesh()))
    spec = placements if placements is not None else shard_spec
    if isinstance(spec, (list, tuple)):
        spec = PartitionSpec(*[None if s in (None, "replicate") else s
                               for s in spec])
    elif spec is None:
        spec = PartitionSpec()
    sharding = NamedSharding(mesh, spec)
    arr = x._data if isinstance(x, Tensor) else x
    if isinstance(arr, jax.core.Tracer):
        out = jax.lax.with_sharding_constraint(arr, sharding)
    else:
        out = jax.device_put(arr, sharding)
    if isinstance(x, Tensor):
        t = Tensor(out, stop_gradient=x.stop_gradient, _internal=True)
        t._grad_node = x._grad_node
        t._out_slot = x._out_slot
        return t
    return out


def shard_op(fn, mesh=None, in_specs=None, out_specs=None):
    """Annotate an op's outputs with shardings (ref shard_op); with GSPMD this is
    just a sharding constraint on the results."""
    def wrapped(*args, **kwargs):
        out = fn(*args, **kwargs)
        if out_specs is None:
            return out
        if isinstance(out, (tuple, list)):
            return type(out)(shard_tensor(o, mesh, s)
                             for o, s in zip(out, out_specs))
        return shard_tensor(out, mesh, out_specs)

    return wrapped


@contextlib.contextmanager
def mesh_context(mesh: Mesh):
    prev = get_mesh()
    set_mesh(mesh)
    try:
        yield mesh
    finally:
        set_mesh(prev)
