"""Collective communication API (ref: `python/paddle/distributed/collective.py` and
`communication/*` — the eager ProcessGroup path over NCCL,
`collective/ProcessGroupNCCL.h:46`).

TPU-native dual path:
- **in-graph** (inside shard_map/pjit with a bound axis): `jax.lax.psum` & co.,
  compiled onto ICI — the analog of the c_* collective ops the static graph inserts
  (`paddle/fluid/operators/collective/`).
- **eager multi-process**: `multihost_utils.process_allgather` + local reduction —
  the analog of ProcessGroup eager calls (correctness path; hot paths belong
  in-graph).

Groups name mesh axes instead of owning NCCL communicators.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.observability import metrics
from paddle_tpu.ops.common import ensure_tensor


def _payload_nbytes(data) -> int:
    """Payload size from shape/dtype — works for concrete arrays AND tracers
    (aval shapes), so in-graph collectives are accounted at trace time."""
    try:
        return int(np.prod(data.shape)) * jnp.dtype(data.dtype).itemsize
    except Exception:  # noqa: BLE001 — accounting must never break the op
        return 0


def _note_collective(op: str, mode: str, *datas):
    """Per-primitive accounting: call count + payload bytes, labeled by
    execution mode. ``in_graph`` counts are trace-time insertions (once per
    compiled program); ``eager``/``local`` count real calls. The byte figure
    is the local payload the primitive moves/produces per participant — the
    EQuARX-style unit for reasoning about comm cost (docs/OBSERVABILITY.md)."""
    metrics.counter("collective.calls", op=op, mode=mode).inc()
    nb = sum(_payload_nbytes(d) for d in datas)
    if nb:
        metrics.counter("collective.bytes", op=op, mode=mode).inc(nb)


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communication group = a set of ranks, optionally bound to a mesh axis
    name for in-graph collectives (ref: `collective.py` Group)."""

    def __init__(self, ranks=None, gid=0, axis_name=None):
        from paddle_tpu.distributed.parallel import get_world_size
        self.ranks = list(ranks) if ranks is not None else \
            list(range(max(get_world_size(), 1)))
        self.id = gid
        self.axis_name = axis_name

    @property
    def nranks(self):
        return len(self.ranks)

    @property
    def world_size(self):
        return len(self.ranks)

    @property
    def rank(self):
        from paddle_tpu.distributed.parallel import get_rank
        r = get_rank()
        return self.ranks.index(r) if r in self.ranks else -1

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def is_member(self):
        from paddle_tpu.distributed.parallel import get_rank
        return get_rank() in self.ranks

    def process_group(self):
        return self

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks}, axis={self.axis_name})"


_group_counter = 0
_groups: dict[int, Group] = {}
_default_group: Group | None = None


def _get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        _default_group = Group(gid=0)
        _groups[0] = _default_group
    return _default_group


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    global _group_counter
    _group_counter += 1
    g = Group(ranks, _group_counter, axis_name=axis_name)
    _groups[g.id] = g
    return g


def get_group(gid=0):
    return _groups.get(gid) or _get_default_group()


def split_group(parent_group=None, split_sizes=None):
    parent = parent_group or _get_default_group()
    out = []
    start = 0
    for size in split_sizes:
        out.append(new_group(parent.ranks[start:start + size]))
        start += size
    return out


def destroy_process_group(group=None):
    global _default_group
    if group is None:
        _groups.clear()
        _default_group = None
    else:
        _groups.pop(group.id, None)


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and not isinstance(tensor._data,
                                                     jax.core.Tracer):
        tensor._data.block_until_ready()


def _in_trace(t: Tensor) -> bool:
    return isinstance(t._data, jax.core.Tracer)


def _axis(group) -> str | None:
    if group is not None and group.axis_name:
        return group.axis_name
    return None


def _multiprocess() -> bool:
    return jax.process_count() > 1


# multi-process allgather transport selection: process_allgather compiles a
# cross-process XLA program, which 0.4.x-era CPU jaxlib cannot do
# ("Multiprocess computations aren't implemented on the CPU backend") — once
# it fails, every later call goes straight to the KV transport
_AG_KV_ONLY = [False]
# allgather sequence counter: all ranks issue eager collectives in the same
# program order, so the counter stays in lockstep (same scheme as _p2p_seq)
_ag_seq = [0]


def _kv_allgather(arr):
    """process_allgather over the coordination-service KV store: each rank
    publishes its array bytes under a sequenced key, then blocking-reads each
    peer's — the TCPStore-analog correctness path for backends that cannot
    compile multiprocess programs. O(P·data) through the coordinator, so it
    is a fallback, not the fast path."""
    from paddle_tpu.distributed import liveness
    from paddle_tpu.distributed.parallel import get_rank
    from paddle_tpu.testing import faults
    if faults.ENABLED:
        # train.collective_stall chaos site (docs/ROBUSTNESS.md): the armed
        # rank sleeps delay_s BEFORE publishing its contribution — from the
        # peers' side indistinguishable from a wedged rank, which is exactly
        # what their liveness monitors must convert into typed PeerLost
        faults.fire("train.collective_stall")
    client = _kv_client()
    np_arr = np.ascontiguousarray(np.asarray(arr))
    seq = _ag_seq[0]
    _ag_seq[0] += 1
    me = get_rank()
    # payload + readiness marker (liveness.set_with_marker): guarded
    # waiters poll the ASCII marker instead of ever letting a blocking
    # read expire (this jaxlib SEGVs on expiring cross-process gets)
    liveness.set_with_marker(client, f"ptpu_ag/{seq}/{me}",
                             np_arr.tobytes())
    parts = []
    for r in range(jax.process_count()):
        if r == me:
            parts.append(np_arr)
            continue
        # liveness-guarded read (distributed/liveness.py): with a monitor
        # installed, a peer that died mid-step converts this would-be-60s
        # opaque wait into a typed PeerLost within the liveness deadline
        raw = liveness.guarded_get_bytes(
            client, f"ptpu_ag/{seq}/{r}", 60_000,
            what=f"allgather seq {seq}")
        parts.append(np.frombuffer(bytes(raw), dtype=np_arr.dtype)
                     .reshape(np_arr.shape))
    try:
        # peers have all read by the barrier: own key is safe to delete, so
        # a long eager loop doesn't grow the coordination service unboundedly
        if liveness.current() is not None:
            # polling barrier: composes with the liveness guard (a peer
            # dying RIGHT HERE still resolves typed, not as a wedged
            # wait_at_barrier); superseded barrier tags from two
            # generations back are provably unread — rank 0 sweeps them
            liveness.kv_barrier(client, f"ag_done/{seq}", rank=me,
                                world=jax.process_count(),
                                timeout_ms=60_000)
            liveness.clear_with_marker(client, f"ptpu_ag/{seq}/{me}")
            if me == 0 and seq >= 2:
                liveness.kv_barrier_cleanup(client, f"ag_done/{seq - 2}")
        else:
            client.wait_at_barrier(f"ptpu_ag_done/{seq}", 60_000)
            client.key_value_delete(f"ptpu_ag/{seq}/{me}")
            client.key_value_delete(f"ptpu_mk/ptpu_ag/{seq}/{me}")
    except liveness.PeerLost:
        raise
    except Exception:  # noqa: BLE001 — cleanup is best-effort
        pass
    return np.stack(parts)


def _proc_allgather(arr):
    if not _AG_KV_ONLY[0]:
        from jax.experimental import multihost_utils
        try:
            return multihost_utils.process_allgather(arr)
        except Exception:  # noqa: BLE001 — backend can't run multiprocess XLA
            _AG_KV_ONLY[0] = True
    return _kv_allgather(arr)


# ------------------------------------------------------------------ collectives


def _rebind(tensor, res):
    """Write a collective's functional result into the user-facing tensor,
    carrying the tape node along (otherwise gradients silently flow through
    the tensor's STALE pre-collective node, or not at all)."""
    tensor._write(res._data)
    if res._grad_node is not None:
        tensor._grad_node = res._grad_node
        tensor._out_slot = res._out_slot
        tensor.stop_gradient = False
    return tensor


def _inplace_apply(tensor, t, fn, op_name):
    """In-place collective on a tape-recorded tensor: the new node's INPUT must
    be a detached proxy carrying the tensor's previous grad node — wiring the
    node onto the same python Tensor object would self-loop the tape and orphan
    everything upstream."""
    from paddle_tpu.core.autograd import apply
    proxy = Tensor(t._data, stop_gradient=t.stop_gradient, _internal=True)
    proxy._grad_node = t._grad_node
    proxy._out_slot = t._out_slot
    if t._grad_node is None and not t.stop_gradient:
        # leaf input: backward would otherwise deposit .grad on the throwaway
        # proxy — redirect the accumulation onto the user-facing tensor
        def _redirect(g):
            if tensor._grad is None:
                tensor._grad = g
            else:
                tensor._grad = Tensor(tensor._grad._data + g._data,
                                      stop_gradient=True, _internal=True)
            return None
        proxy.register_hook(_redirect)
    res = apply(fn, proxy, op_name=op_name)
    return _rebind(tensor, res)


def _note_quantized(mode: str, q, scales):
    """Quantized-collective accounting: the bytes figure is what the wire
    MOVES — the int8 blocks plus their f32 scales, ~1/3.8 of the f32
    payload at the default block size (the EQuARX argument, arxiv
    2506.17615; bench_quant asserts the >= 3x reduction via these
    counters)."""
    from paddle_tpu.quantization.comms import quantized_payload_nbytes
    metrics.counter("collective.calls", op="all_reduce", mode=mode).inc()
    metrics.counter("collective.quantized_calls").inc()
    metrics.counter("collective.bytes", op="all_reduce", mode=mode).inc(
        quantized_payload_nbytes(q, scales))


def _quantized_all_reduce(tensor, t, op, axis, quant_block):
    """Blockwise abs-max int8 allreduce (EQuARX-style, arxiv 2506.17615;
    docs/QUANTIZATION.md): quantize the local payload into int8 blocks +
    per-block f32 scales, move THOSE, dequantize each participant's blocks
    and reduce in f32. Error is bounded per block (comms.roundtrip_bound),
    pinned by tests/test_quantization.py. SUM/AVG only — MAX/MIN/PROD gain
    nothing from a lossy codec and are refused loudly."""
    from paddle_tpu.quantization import comms
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(
            f"quantized all_reduce supports SUM/AVG, got {op!r}")
    if _in_trace(t) and axis is not None:
        def prim(a):
            q, s, meta = comms.quantize_blockwise(a, quant_block)
            _note_quantized("in_graph", q, s)
            gq = jax.lax.all_gather(q, axis)           # int8 on the wire
            gs = jax.lax.all_gather(s, axis)
            total = jnp.sum(comms.dequantize_blockwise(
                gq, gs, (a.shape, int(np.prod(a.shape)), jnp.float32)),
                axis=0)
            if op == ReduceOp.AVG:
                total = total / jax.lax.psum(1, axis)
            return total.astype(a.dtype)
        return _inplace_apply(tensor, t, prim, "all_reduce")
    q, s, meta = comms.quantize_blockwise(t._data, quant_block)
    _note_quantized("eager" if _multiprocess() else "local", q, s)
    if _multiprocess():
        gq = _proc_allgather(q)                        # int8 through the KV
        gs = _proc_allgather(s)                        # transport: ~1/4 bytes
        total = jnp.sum(comms.dequantize_blockwise(
            jnp.asarray(gq), jnp.asarray(gs),
            (t._data.shape, int(np.prod(t._data.shape)), jnp.float32)),
            axis=0)
        if op == ReduceOp.AVG:
            total = total / jax.process_count()
        tensor._write(total.astype(t.dtype))
    else:
        # 1 participant: the quantize/dequantize round trip still applies,
        # so single-process numerics match the multi-process semantics
        # (tests pin the documented bound against exactly this path)
        tensor._write(comms.dequantize_blockwise(q, s, meta)
                      .astype(t.dtype))
    return tensor


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               quantized=False, quant_block=None):
    """In-graph: lax.psum over the group's mesh axis. Eager multi-process:
    process allgather + local reduce. Single process: identity (1 rank).

    ``quantized=True`` opts into the blockwise abs-max int8 payload codec
    (EQuARX-style, arxiv 2506.17615): SUM/AVG move ~1/4 the wire bytes at a
    per-block-bounded numeric error (docs/QUANTIZATION.md; the
    `collective.bytes` counter records the QUANTIZED payload, so the wire
    reduction is provable from the metrics snapshot). ``quant_block`` sets
    the codec block size (default `quantization.comms.DEFAULT_BLOCK`)."""
    t = ensure_tensor(tensor)
    axis = _axis(group)
    if quantized:
        from paddle_tpu.quantization.comms import DEFAULT_BLOCK
        return _quantized_all_reduce(tensor, t, op, axis,
                                     int(quant_block or DEFAULT_BLOCK))
    if _in_trace(t) and axis is not None:
        _note_collective("all_reduce", "in_graph", t._data)
        red = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
               ReduceOp.MIN: jax.lax.pmin, ReduceOp.AVG: jax.lax.pmean,
               # no pprod primitive: gather + local product
               ReduceOp.PROD: lambda a, ax: jnp.prod(
                   jax.lax.all_gather(a, ax), axis=0)}[op]
        return _inplace_apply(tensor, t, lambda a: red(a, axis), "all_reduce")
    _note_collective("all_reduce", "eager" if _multiprocess() else "local",
                     t._data)
    if _multiprocess():
        stacked = _proc_allgather(t._data)
        fn = {ReduceOp.SUM: jnp.sum, ReduceOp.MAX: jnp.max, ReduceOp.MIN: jnp.min,
              ReduceOp.PROD: jnp.prod,
              ReduceOp.AVG: jnp.mean}[op]
        tensor._write(fn(stacked, axis=0).astype(t.dtype))
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    t = ensure_tensor(tensor)
    ax = _axis(group)
    in_graph = _in_trace(t) and ax is not None
    _note_collective("all_gather", "in_graph" if in_graph else
                     ("eager" if _multiprocess() else "local"), t._data)
    if in_graph:
        from paddle_tpu.core.autograd import apply
        res = apply(lambda a: jax.lax.all_gather(a, ax), t, op_name="all_gather")
        n = res.shape[0]
        for i in range(n):
            tensor_list.append(res[i])
        return tensor_list
    if _multiprocess():
        stacked = _proc_allgather(t._data)
        for i in range(stacked.shape[0]):
            tensor_list.append(Tensor(stacked[i], _internal=True))
    else:
        tensor_list.append(Tensor(t._data, _internal=True))
    return tensor_list


def all_gather_object(object_list, obj, group=None):
    import pickle
    if not _multiprocess():
        object_list.append(obj)
        return object_list
    payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    sizes = _proc_allgather(jnp.asarray([payload.size], jnp.int64))
    maxlen = int(np.max(np.asarray(sizes)))
    padded = np.zeros(maxlen, np.uint8)
    padded[: payload.size] = payload
    gathered = np.asarray(_proc_allgather(jnp.asarray(padded)))
    for row, size in zip(gathered, np.asarray(sizes).reshape(-1)):
        object_list.append(pickle.loads(row[: int(size)].tobytes()))
    return object_list


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    all_reduce(tensor, op=op, group=group)
    return tensor


def broadcast(tensor, src, group=None, sync_op=True):
    t = ensure_tensor(tensor)
    ax = _axis(group)
    if _in_trace(t) and ax is not None:
        _note_collective("broadcast", "in_graph", t._data)
        # in-SPMD broadcast from src: select src's shard via all_gather + index
        return _inplace_apply(tensor, t,
                              lambda a: jax.lax.all_gather(a, ax)[src],
                              "broadcast")
    _note_collective("broadcast", "eager" if _multiprocess() else "local",
                     t._data)
    if _multiprocess():
        stacked = _proc_allgather(t._data)
        tensor._write(jnp.asarray(stacked[src]))
    return tensor


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    from paddle_tpu.distributed.parallel import get_rank
    _note_collective("scatter", "eager" if _multiprocess() else "local",
                     ensure_tensor(tensor)._data)
    if not _multiprocess():
        if tensor_list:
            tensor._write(ensure_tensor(tensor_list[0])._data)
        return tensor
    rank = get_rank()
    world = jax.process_count()
    # right-sized p2p through the coordination-service KV (each rank moves
    # O(data/P), not the O(P*data) broadcast-everything emulation)
    if rank == src:
        if not tensor_list:
            raise ValueError("scatter src needs tensor_list")
        for r in range(world):
            chunk = ensure_tensor(tensor_list[r])
            if r == rank:
                tensor._write(chunk._data)
            else:
                from paddle_tpu.distributed import liveness
                n, key = _p2p_peek_key(src, r)
                liveness.set_with_marker(
                    _kv_client(), key, np.ascontiguousarray(
                        np.asarray(chunk._data)).tobytes())
                _p2p_advance(src, r, n)
    else:
        recv(tensor, src=src)
    return tensor


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    t0 = ensure_tensor(tensor_list[0] if isinstance(tensor_list, (list, tuple))
                       else tensor_list)
    ax = _axis(group)
    if _in_trace(t0) and ax is not None:
        _note_collective("reduce_scatter", "in_graph",
                         *[ensure_tensor(x)._data for x in tensor_list])
        from paddle_tpu.core.autograd import apply
        stacked = [ensure_tensor(x) for x in tensor_list]
        res = apply(lambda *arrs: jax.lax.psum_scatter(
            jnp.concatenate(arrs, axis=0), ax, tiled=True), *stacked,
            op_name="reduce_scatter")
        return _rebind(tensor, res)
    _note_collective("reduce_scatter",
                     "eager" if _multiprocess() else "local",
                     *([ensure_tensor(x)._data for x in tensor_list]
                       if isinstance(tensor_list, (list, tuple))
                       else [t0._data]))
    if _multiprocess():
        from paddle_tpu.distributed.parallel import get_rank
        local = jnp.stack([ensure_tensor(x)._data for x in tensor_list])
        summed = jnp.sum(_proc_allgather(local), axis=0)
        tensor._write(summed[get_rank()])
    else:
        tensor._write(t0._data)
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    if out_tensor_list is None:
        out_tensor_list = []
    ts = [ensure_tensor(x) for x in in_tensor_list]
    ax = _axis(group)
    _note_collective("alltoall",
                     "in_graph" if (ts and _in_trace(ts[0]) and ax is not None)
                     else ("eager" if _multiprocess() else "local"),
                     *[t._data for t in ts])
    if ts and _in_trace(ts[0]) and ax is not None:
        # in-graph: rank r's output[j] = rank j's input[r] (lax.all_to_all on
        # the stacked chunk axis — the global_scatter/gather building block)
        from paddle_tpu.core.autograd import apply
        res = apply(lambda *a: jax.lax.all_to_all(
            jnp.stack(a), ax, split_axis=0, concat_axis=0, tiled=False),
            *ts, op_name="alltoall")
        for i in range(len(ts)):
            out_tensor_list.append(res[i])
        return out_tensor_list
    if not _multiprocess():
        for t in ts:
            out_tensor_list.append(t)
        return out_tensor_list
    from paddle_tpu.distributed.parallel import get_rank
    rank = get_rank()
    world = jax.process_count()
    # pairwise exchange through the KV transport: O(data/P) per peer instead
    # of the former allgather-everything emulation
    client = _kv_client()
    from paddle_tpu.distributed import liveness
    for r in range(world):
        if r == rank:
            continue
        n, key = _p2p_peek_key(rank, r)
        liveness.set_with_marker(
            client, key,
            np.ascontiguousarray(np.asarray(ts[r]._data)).tobytes())
        _p2p_advance(rank, r, n)
    for r in range(world):
        if r == rank:
            out_tensor_list.append(Tensor(ts[rank]._data, _internal=True))
            continue
        n, key = _p2p_peek_key(r, rank)
        raw = liveness.guarded_get_bytes(client, key, 120_000,
                                         what=f"alltoall from rank {r}")
        _p2p_advance(r, rank, n)
        liveness.clear_with_marker(client, key)
        arr = np.frombuffer(raw, dtype=np.dtype(str(ts[r]._data.dtype))
                            ).reshape(ts[r].shape)
        out_tensor_list.append(Tensor(jnp.asarray(arr), _internal=True))
    return out_tensor_list


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    t = ensure_tensor(in_tensor)
    ax = _axis(group)
    _note_collective("alltoall_single",
                     "in_graph" if (_in_trace(t) and ax is not None)
                     else ("eager" if _multiprocess() else "local"), t._data)
    if _in_trace(t) and ax is not None:
        from paddle_tpu.core.autograd import apply
        n = group.nranks
        res = apply(lambda a: jax.lax.all_to_all(
            a.reshape((n, -1) + a.shape[1:]), ax, split_axis=0, concat_axis=0,
            tiled=False).reshape(a.shape), t, op_name="alltoall_single")
        if out_tensor is not None:
            return _rebind(out_tensor, res)
        return res
    if out_tensor is not None and not _multiprocess():
        out_tensor._write(t._data)
        return out_tensor
    if _multiprocess():
        from paddle_tpu.distributed.parallel import get_rank, get_world_size
        n = get_world_size()
        chunks = jnp.stack(jnp.split(t._data, n, axis=0))
        gathered = _proc_allgather(chunks)  # [P, P, chunk...]
        rank = get_rank()
        mine = jnp.concatenate([jnp.asarray(gathered[p][rank])
                                for p in range(n)], axis=0)
        if out_tensor is not None:
            out_tensor._write(mine)
            return out_tensor
        return Tensor(mine, _internal=True)
    return t


# p2p sequence counters, keyed (src, dst) — both ends advance in lockstep.
# The counter only advances AFTER a successful transfer, so a timed-out recv
# retries the same sequence number instead of silently skipping a message.
_p2p_seq: dict = {}


def _p2p_peek_key(src, dst):
    n = _p2p_seq.get((src, dst), 0)
    return n, f"ptpu_p2p/{src}to{dst}/{n}"


def _p2p_advance(src, dst, n):
    _p2p_seq[(src, dst)] = n + 1


def _kv_client():
    from jax._src.distributed import global_state
    if global_state.client is None:
        raise RuntimeError("p2p needs init_parallel_env (jax.distributed)")
    return global_state.client


def send(tensor, dst=0, group=None, sync_op=True):
    """Point-to-point send (ref `send_v2` op / ProcessGroup::Send).

    In-graph p2p: inside shard_map a matched send/recv pair is one
    lax.ppermute — that is how the SPMD pipeline engine moves activations
    between stages (`fleet/pipeline.py` spmd_pipeline, counterpart of the
    reference's `p2p_communication.py:74`); calling this inside a trace
    raises with that pointer.

    Eager multi-process: the payload travels through the coordination
    service's KV store (the TCPStore analog) — a correctness path for
    control-plane-sized tensors, like the reference's Gloo fallback."""
    t = ensure_tensor(tensor)
    if _in_trace(t):
        raise NotImplementedError(
            "asymmetric eager p2p is not expressible in one SPMD program; "
            "matched send/recv pairs compile to lax.ppermute — see "
            "paddle_tpu.distributed.fleet.pipeline.spmd_pipeline")
    if not _multiprocess():
        raise RuntimeError("send() with world_size 1 has no peer")
    _note_collective("send", "eager", t._data)
    from paddle_tpu.distributed import liveness
    from paddle_tpu.distributed.parallel import get_rank
    arr = np.ascontiguousarray(np.asarray(t._data))
    n, key = _p2p_peek_key(get_rank(), dst)
    liveness.set_with_marker(_kv_client(), key, arr.tobytes())
    _p2p_advance(get_rank(), dst, n)


def recv(tensor, src=0, group=None, sync_op=True):
    """Point-to-point receive into ``tensor`` (shape/dtype taken from it;
    ref `recv_v2` op / ProcessGroup::Recv). See send() for the transport."""
    t = ensure_tensor(tensor)
    if _in_trace(t):
        raise NotImplementedError(
            "asymmetric eager p2p is not expressible in one SPMD program; "
            "matched send/recv pairs compile to lax.ppermute — see "
            "paddle_tpu.distributed.fleet.pipeline.spmd_pipeline")
    if not _multiprocess():
        raise RuntimeError("recv() with world_size 1 has no peer")
    _note_collective("recv", "eager", t._data)
    from paddle_tpu.distributed import liveness
    from paddle_tpu.distributed.parallel import get_rank
    n, key = _p2p_peek_key(src, get_rank())
    client = _kv_client()
    raw = liveness.guarded_get_bytes(client, key, 120_000,
                                     what=f"recv from rank {src}")
    _p2p_advance(src, get_rank(), n)
    # free the coordinator's copy (payload + readiness marker) — otherwise
    # every payload ever sent accumulates in the coordination service
    liveness.clear_with_marker(client, key)
    arr = np.frombuffer(raw, dtype=np.dtype(str(t._data.dtype))).reshape(
        t.shape)
    t._write(jnp.asarray(arr))
    return t


def isend(tensor, dst, group=None):
    send(tensor, dst, group)
    return _DoneTask()


def irecv(tensor, src=None, group=None):
    recv(tensor, src if src is not None else 0, group)
    return _DoneTask()


class _DoneTask:
    """Completed-task handle (the eager KV transport is synchronous)."""

    def wait(self):
        return True

    def is_completed(self):
        return True
