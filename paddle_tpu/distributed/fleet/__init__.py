"""paddle.distributed.fleet — hybrid-parallel facade.

Ref: `python/paddle/distributed/fleet/fleet.py` (Fleet singleton, init :168,
distributed_optimizer :1032), topology (`fleet/base/topology.py:53,139`),
DistributedStrategy (`fleet/base/distributed_strategy.py:111`).
"""
from paddle_tpu.distributed.fleet.base import (  # noqa: F401
    DistributedStrategy, CommunicateTopology, HybridCommunicateGroup,
    PaddleCloudRoleMaker, UserDefinedRoleMaker,
)
from paddle_tpu.distributed.fleet.fleet import (  # noqa: F401
    Fleet, init, distributed_model, distributed_optimizer, get_hybrid_communicate_group,
    worker_index, worker_num, is_first_worker, barrier_worker,
)
from paddle_tpu.distributed.fleet import meta_parallel  # noqa: F401
from paddle_tpu.distributed.fleet.meta_parallel import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy, PipelineLayer, LayerDesc, SharedLayerDesc,
    TensorParallel, PipelineParallel, get_rng_state_tracker,
)
from paddle_tpu.distributed.fleet.recompute import recompute, recompute_sequential  # noqa: F401
from paddle_tpu.distributed.fleet.meta_optimizers import (  # noqa: F401
    GradientMergeOptimizer, LocalSGDOptimizer, DGCOptimizer,
    FP16AllreduceOptimizer, apply_meta_optimizers,
)
from paddle_tpu.distributed.fleet import utils_mod as utils  # noqa: F401
