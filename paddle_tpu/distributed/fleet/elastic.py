"""Elastic / fault detection.

Counterpart of the reference `ElasticManager`
(`python/paddle/distributed/fleet/elastic/manager.py:126`): etcd leases +
watches detecting dead hosts and rebuilding the job. TPU reality check
(SURVEY §5.3/§7 hard-part #7): slices cannot add/remove single hosts freely,
so elasticity degrades to FAULT DETECTION + whole-pod restart from the latest
checkpoint — which is what this implements, file-heartbeat based (no etcd
dependency; the launch controller is the restart authority).

- workers: ``start_heartbeat(path)`` (init_parallel_env starts it
  automatically when the launcher sets PADDLE_HEARTBEAT_FILE);
- controller: ``ElasticManager.dead_workers()`` reports ranks whose heartbeat
  went stale; the launch watch loop treats staleness like a crash and applies
  its restart policy (--max_restarts).
"""
from __future__ import annotations

import os
import re
import threading
import time


def _check_registry_member(node_id, endpoint):
    """Shared observer-mode guard for both registry backends: a registry
    constructed without node_id/endpoint only watches membership."""
    if node_id is None or endpoint is None:
        raise RuntimeError(
            "observer-mode registry (no node_id/endpoint) cannot "
            "register or leave — it only watches membership")


# ------------------------------------------------------------ node roles
#
# Control-plane HA + disaggregated serving (docs/ROBUSTNESS.md
# "Control-plane HA", docs/SERVING.md "Disaggregated serving"): routers
# AND tiered replicas are registry citizens under distinct roles so
# nobody mistakes one for a plain engine replica. The role rides the
# node ID as a ``<role>:`` prefix — the registry value format (endpoint
# string) stays untouched, so every existing lease keeps working: an
# UNPREFIXED id IS a replica (legacy, test-pinned). One parser serves
# every role: ``router:<id>`` (control plane, never in any replica
# rotation), ``prefill:<id>`` / ``decode:<id>`` (the disaggregated
# serving tiers), and any future role a subsystem mints via
# `role_node_id` — `node_role` returns the prefix verbatim.

ROUTER_ROLE_PREFIX = "router:"

# a role token is a short lowercase word; anything else before a ":" is
# part of a legacy replica id (e.g. an id that embeds an endpoint), not
# a role — the conservative parse keeps every pre-role lease a replica
_ROLE_RE = re.compile(r"^[a-z][a-z0-9_-]{0,31}$")


def role_node_id(role, node_id) -> str:
    """Registry node id for a ``role`` lease: ``<role>:<id>``. The role
    must be a valid role token (lowercase word) — a typo'd role would
    otherwise silently parse back as a legacy replica."""
    role = str(role)
    if not _ROLE_RE.match(role):
        raise ValueError(f"invalid role token {role!r} "
                         f"(want a short lowercase word)")
    return f"{role}:{node_id}"


def router_node_id(router_id) -> str:
    """Registry node id for a router lease: ``router:<id>``."""
    return role_node_id("router", router_id)


def node_role(node_id) -> str:
    """The ``<role>:``-prefixed lease's role (``"router"``,
    ``"prefill"``, ``"decode"``, ...); ``"replica"`` for everything else
    — including every pre-role lease and any id whose colon prefix is
    not a role token (legacy ids are replicas, test-pinned)."""
    s = str(node_id)
    head, sep, _rest = s.partition(":")
    if sep and _ROLE_RE.match(head):
        return head
    return "replica"


def start_heartbeat(path, interval=2.0):
    """Touch `path` every `interval` seconds from a daemon thread."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def beat():
        while True:
            try:
                with open(path, "w") as f:
                    f.write(str(time.time()))
            except OSError:
                pass
            time.sleep(interval)

    t = threading.Thread(target=beat, daemon=True, name="paddle-heartbeat")
    t.start()
    return t


class NodeRegistry:
    """Multi-node membership registry (ref the etcd node registry,
    `fleet/elastic/manager.py:126,240-257`): every HOST publishes
    ``node_<id>.json`` {endpoint, ts} under a shared directory and renews it
    on a lease-like heartbeat; peers observe join/leave by polling mtime
    freshness. A shared filesystem (the NFS/GCS mount every TPU pod has)
    replaces etcd — the semantics map 1:1 (register = write, lease = mtime
    TTL, watch = poll, delete = leave).

    CONSTRAINT (loud, r4 verdict weak #6): this backend only coordinates
    hosts that mount the SAME directory. For clusters without one, use
    :class:`TcpNodeRegistry` against a :class:`TcpRegistryServer` — same
    surface, no filesystem assumption.

    OBSERVER MODE: a process that only WATCHES membership (the serving
    router, a controller) constructs the registry with ``node_id=None`` —
    ``alive_nodes()`` works, ``register()``/``leave()`` refuse."""

    def __init__(self, registry_dir, node_id=None, endpoint=None, ttl=30.0,
                 heartbeat_interval=2.0):
        self.dir = registry_dir
        self.node_id = None if node_id is None else str(node_id)
        self.endpoint = endpoint
        self.ttl = ttl
        self._interval = heartbeat_interval
        self._stop = threading.Event()
        self._thread = None
        os.makedirs(registry_dir, exist_ok=True)

    def _check_member(self):
        _check_registry_member(self.node_id, self.endpoint)

    def _path(self, node_id=None):
        return os.path.join(self.dir, f"node_{node_id or self.node_id}.json")

    def _write(self):
        import json
        tmp = self._path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"endpoint": self.endpoint, "ts": time.time(),
                       "ttl": self.ttl}, f)
        os.replace(tmp, self._path())

    def register(self):
        """Publish this node and keep renewing the lease (daemon thread)."""
        self._check_member()
        self._write()

        def renew():
            while not self._stop.wait(self._interval):
                try:
                    self._write()
                except OSError:
                    pass

        self._thread = threading.Thread(target=renew, daemon=True,
                                        name="paddle-node-lease")
        self._thread.start()
        return self

    def leave(self):
        self._check_member()
        self._stop.set()
        if self._thread is not None:
            # join before unlinking: an in-flight _write() could otherwise
            # land after the remove and resurrect the lease for a full TTL
            self._thread.join(timeout=self._interval + 1.0)
        try:
            os.remove(self._path())
        except OSError:
            pass

    def alive_nodes(self):
        """{node_id: endpoint} for every node with a fresh lease."""
        import json
        now = time.time()
        out = {}
        for name in sorted(os.listdir(self.dir)):
            if not (name.startswith("node_") and name.endswith(".json")):
                continue
            p = os.path.join(self.dir, name)
            try:
                with open(p) as f:
                    info = json.load(f)
                # per-lease TTL, like etcd leases (observer honors the
                # registrant's own renewal contract)
                if now - os.path.getmtime(p) > info.get("ttl", self.ttl):
                    continue
            except (OSError, ValueError):
                continue
            out[name[len("node_"):-len(".json")]] = info["endpoint"]
        return out


class ElasticJobManager:
    """np-range elasticity (ref ``--np 2:4`` + `manager.py` scale
    detection): watches the registry and tells the launch controller what
    to do — WAIT below np_min, RESCALE when the committed member set
    changed within [np_min, np_max] (rebuild PADDLE_TRAINER_ENDPOINTS and
    restart from the latest auto-checkpoint — `incubate/checkpoint.py`
    resumes the epoch), STEADY otherwise."""

    WAIT, STEADY, RESCALE = "wait", "steady", "rescale"

    def __init__(self, registry, np_min, np_max=None):
        self.registry = registry
        self.np_min = int(np_min)
        self.np_max = int(np_max or np_min)
        self._committed = None

    def endpoints(self, alive):
        return [alive[k] for k in sorted(alive)]

    def poll(self):
        alive = self.registry.alive_nodes()
        n = len(alive)
        if n < self.np_min:
            # forget the committed set: when quorum returns — even with the
            # IDENTICAL members — the stopped job must be relaunched
            # (RESCALE), not reported STEADY
            self._committed = None
            return self.WAIT, self.endpoints(alive)
        members = tuple(sorted(alive))[: self.np_max]
        eps = [alive[k] for k in members]
        if self._committed is None:
            self._committed = members
            return self.RESCALE, eps          # first commit = initial launch
        if members != self._committed:
            self._committed = members
            return self.RESCALE, eps
        return self.STEADY, eps


class ElasticManager:
    """Controller-side staleness watcher (ref `manager.py:126` liveness
    role) — single-pod fault detection; multi-node membership lives in
    :class:`NodeRegistry` + :class:`ElasticJobManager`."""

    def __init__(self, heartbeat_dir, world_size, timeout=30.0,
                 grace_period=60.0):
        self.dir = heartbeat_dir
        self.world_size = world_size
        self.timeout = timeout
        self._start = time.time()
        self.grace = grace_period

    def path_for(self, rank):
        return os.path.join(self.dir, f"heartbeat.{rank}")

    def reset(self):
        """Called by the controller before a pod restart: old heartbeat files
        must not instantly re-flag the fresh workers as stale, and the grace
        window restarts (new workers need import/init time)."""
        for rank in range(self.world_size):
            try:
                os.remove(self.path_for(rank))
            except OSError:
                pass
        self._start = time.time()

    def dead_workers(self):
        """Ranks whose heartbeat is stale. Within the startup grace period a
        missing file is not a death (workers may still be importing jax)."""
        now = time.time()
        dead = []
        for rank in range(self.world_size):
            p = self.path_for(rank)
            try:
                age = now - os.path.getmtime(p)
            except OSError:
                if now - self._start > self.grace:
                    dead.append(rank)
                continue
            if age > self.timeout:
                dead.append(rank)
        return dead

    def healthy(self):
        return not self.dead_workers()


# --------------------------------------------------------------- TCP backend

def _elastic_token() -> bytes:
    """Shared-secret digest for registry connections (same contract as
    `distributed/rpc.py`): set the SAME ``PADDLE_ELASTIC_TOKEN`` on every
    host. There is deliberately no default — the old constant fallback
    ("pt-elastic") let anyone who could reach the port tamper with
    membership (r5 advisor), and a per-process random token cannot work
    for a registry whose whole point is cross-host agreement."""
    import hashlib
    secret = os.environ.get("PADDLE_ELASTIC_TOKEN")
    if not secret:
        raise RuntimeError(
            "PADDLE_ELASTIC_TOKEN is not set: the TCP elastic registry "
            "refuses to run with a well-known default secret. Export the "
            "same PADDLE_ELASTIC_TOKEN on the registry host and every "
            "agent host.")
    return hashlib.sha256(secret.encode()).digest()


class TcpRegistryServer:
    """In-memory lease store over TCP — the etcd-replacement for clusters
    WITHOUT a shared filesystem (r4 verdict weak #6: the directory-based
    :class:`NodeRegistry` assumes every host mounts the same dir; the
    reference's etcd registry has no such constraint,
    `fleet/elastic/manager.py:126`). Run one instance next to the launch
    controller: ``python -m paddle_tpu.distributed.fleet.elastic --port P``
    or ``TcpRegistryServer(port=...).start()``.

    Wire protocol (authed like rpc.py): 32-byte sha256 hello, then
    newline-delimited JSON requests {op: put|del|list, ...} -> JSON reply.
    Leases live in memory with per-entry TTLs; LIST filters stale."""

    def __init__(self, host="127.0.0.1", port=0):
        import socket
        self._nodes = {}          # node_id -> (endpoint, ts, ttl, nonce)
        self._tombstones = {}     # (node_id, nonce) -> del timestamp
        self._lock = threading.Lock()
        self._token = _elastic_token()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="pt-elastic-registry")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def _serve(self):
        import socket
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.5)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._client, args=(conn,),
                             daemon=True).start()

    def _client(self, conn):
        import hmac
        import json
        try:
            conn.settimeout(10.0)
            hello = b""
            while len(hello) < 32:
                chunk = conn.recv(32 - len(hello))
                if not chunk:
                    return
                hello += chunk
            if not hmac.compare_digest(hello, self._token):
                return
            f = conn.makefile("rwb")
            for line in f:
                try:
                    req = json.loads(line)
                except ValueError as e:
                    # authed but unparsable: reply per protocol, then drop
                    # (stream position after a bad line is unknowable)
                    f.write((json.dumps({"ok": False,
                                         "error": f"bad json: {e}"})
                             + "\n").encode())
                    f.flush()
                    return
                op = req.get("op")
                now = time.time()
                try:
                    with self._lock:
                        if op == "put":
                            nid = str(req["node_id"])
                            nonce = str(req.get("nonce", ""))
                            # a put whose SESSION was already deleted is a
                            # late in-flight renewal racing leave() — drop
                            # it (sequencing, not timing, closes the lease-
                            # resurrection race); a REJOIN uses a fresh
                            # nonce and registers normally
                            if (nid, nonce) in self._tombstones:
                                resp = {"ok": True, "stale": True}
                            else:
                                self._nodes[nid] = (
                                    req["endpoint"], now,
                                    float(req.get("ttl", 30)), nonce)
                                resp = {"ok": True}
                        elif op == "del":
                            nid = str(req["node_id"])
                            nonce = str(req.get("nonce", ""))
                            self._tombstones[(nid, nonce)] = now
                            cur = self._nodes.get(nid)
                            if cur is None or cur[3] == nonce or not nonce:
                                self._nodes.pop(nid, None)
                            resp = {"ok": True}
                        elif op == "list":
                            # prune expired leases + old tombstones
                            # (node-id churn across elastic restarts must
                            # not grow the dicts unboundedly)
                            dead = [k for k, (_, ts, ttl, _n)
                                    in self._nodes.items()
                                    if now - ts > ttl]
                            for k in dead:
                                del self._nodes[k]
                            for k in [k for k, ts in
                                      self._tombstones.items()
                                      if now - ts > 120.0]:
                                del self._tombstones[k]
                            resp = {"ok": True, "nodes": {
                                k: ep for k, (ep, ts, ttl, _n)
                                in self._nodes.items()}}
                        else:
                            resp = {"ok": False, "error": f"bad op {op!r}"}
                except (KeyError, TypeError, ValueError) as e:
                    # malformed-but-authed request: reply with the error the
                    # protocol promises instead of killing the handler
                    resp = {"ok": False,
                            "error": f"{type(e).__name__}: {e}"}
                f.write((json.dumps(resp) + "\n").encode())
                f.flush()
        except OSError:
            pass
        finally:
            conn.close()


class TcpNodeRegistry:
    """Drop-in for :class:`NodeRegistry` backed by a
    :class:`TcpRegistryServer` instead of a shared directory — same
    register()/leave()/alive_nodes() surface (observer mode with
    ``node_id=None`` included), so :class:`ElasticJobManager` and the
    serving router work with either backend unchanged."""

    def __init__(self, server_addr, node_id=None, endpoint=None, ttl=30.0,
                 heartbeat_interval=2.0):
        host, port = server_addr.rsplit(":", 1)
        self._addr = (host, int(port))
        self.node_id = None if node_id is None else str(node_id)
        self.endpoint = endpoint
        self.ttl = ttl
        self._interval = heartbeat_interval
        self._stop = threading.Event()
        self._thread = None
        self._last_view: dict = {}
        self._nonce = os.urandom(8).hex()   # session id: dedupes vs rejoin

    def _call(self, req):
        import json
        import socket
        with socket.create_connection(self._addr, timeout=10.0) as s:
            s.sendall(_elastic_token())
            f = s.makefile("rwb")
            f.write((json.dumps(req) + "\n").encode())
            f.flush()
            line = f.readline()
            if not line:
                raise ConnectionError("registry closed (bad auth token?)")
            return json.loads(line)

    def _check_member(self):
        _check_registry_member(self.node_id, self.endpoint)

    def register(self):
        self._check_member()
        self._call({"op": "put", "node_id": self.node_id,
                    "endpoint": self.endpoint, "ttl": self.ttl,
                    "nonce": self._nonce})

        def renew():
            while not self._stop.wait(self._interval):
                try:
                    self._call({"op": "put", "node_id": self.node_id,
                                "endpoint": self.endpoint, "ttl": self.ttl,
                                "nonce": self._nonce})
                except (OSError, ValueError):
                    pass

        self._thread = threading.Thread(target=renew, daemon=True,
                                        name="paddle-node-lease-tcp")
        self._thread.start()
        return self

    def leave(self):
        self._check_member()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._interval + 1.0)
        try:
            # the del TOMBSTONES this session's nonce server-side, so even
            # a renewal still in flight (socket timeouts can hold one for
            # tens of seconds) cannot resurrect the lease — sequencing,
            # not join-timing, closes the race; a rejoining registry uses
            # a fresh nonce and is unaffected
            self._call({"op": "del", "node_id": self.node_id,
                        "nonce": self._nonce})
        except (OSError, ValueError):
            pass

    def alive_nodes(self):
        """Degrades like the file backend: a transient registry outage
        (server restarting, dropped connect) returns the LAST successful
        view instead of crashing the elastic controller — the controller
        holds steady through registry churn and reconverges on the next
        successful poll."""
        try:
            resp = self._call({"op": "list"})
        except (OSError, ValueError):
            return dict(self._last_view)
        self._last_view = dict(resp.get("nodes", {}))
        return dict(self._last_view)


def _registry_main(argv=None):
    import argparse
    ap = argparse.ArgumentParser("paddle_tpu.distributed.fleet.elastic")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)
    srv = TcpRegistryServer(args.host, args.port).start()
    print(f"REGISTRY LISTENING {srv.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    _registry_main()
