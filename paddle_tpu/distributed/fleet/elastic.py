"""Elastic / fault detection.

Counterpart of the reference `ElasticManager`
(`python/paddle/distributed/fleet/elastic/manager.py:126`): etcd leases +
watches detecting dead hosts and rebuilding the job. TPU reality check
(SURVEY §5.3/§7 hard-part #7): slices cannot add/remove single hosts freely,
so elasticity degrades to FAULT DETECTION + whole-pod restart from the latest
checkpoint — which is what this implements, file-heartbeat based (no etcd
dependency; the launch controller is the restart authority).

- workers: ``start_heartbeat(path)`` (init_parallel_env starts it
  automatically when the launcher sets PADDLE_HEARTBEAT_FILE);
- controller: ``ElasticManager.dead_workers()`` reports ranks whose heartbeat
  went stale; the launch watch loop treats staleness like a crash and applies
  its restart policy (--max_restarts).
"""
from __future__ import annotations

import os
import threading
import time


def start_heartbeat(path, interval=2.0):
    """Touch `path` every `interval` seconds from a daemon thread."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def beat():
        while True:
            try:
                with open(path, "w") as f:
                    f.write(str(time.time()))
            except OSError:
                pass
            time.sleep(interval)

    t = threading.Thread(target=beat, daemon=True, name="paddle-heartbeat")
    t.start()
    return t


class ElasticManager:
    """Controller-side staleness watcher (ref `manager.py:126` liveness role;
    np ranges / scale-up have no TPU-slice analog and are not pretended)."""

    def __init__(self, heartbeat_dir, world_size, timeout=30.0,
                 grace_period=60.0):
        self.dir = heartbeat_dir
        self.world_size = world_size
        self.timeout = timeout
        self._start = time.time()
        self.grace = grace_period

    def path_for(self, rank):
        return os.path.join(self.dir, f"heartbeat.{rank}")

    def reset(self):
        """Called by the controller before a pod restart: old heartbeat files
        must not instantly re-flag the fresh workers as stale, and the grace
        window restarts (new workers need import/init time)."""
        for rank in range(self.world_size):
            try:
                os.remove(self.path_for(rank))
            except OSError:
                pass
        self._start = time.time()

    def dead_workers(self):
        """Ranks whose heartbeat is stale. Within the startup grace period a
        missing file is not a death (workers may still be importing jax)."""
        now = time.time()
        dead = []
        for rank in range(self.world_size):
            p = self.path_for(rank)
            try:
                age = now - os.path.getmtime(p)
            except OSError:
                if now - self._start > self.grace:
                    dead.append(rank)
                continue
            if age > self.timeout:
                dead.append(rank)
        return dead

    def healthy(self):
        return not self.dead_workers()
