"""fleet.utils — rank-aware logging + filesystem + hybrid-parallel helpers
(ref `python/paddle/distributed/fleet/utils/`: `log_util.py` logger,
`fs.py` LocalFS, `hybrid_parallel_util.py` fused sync helpers).
"""
from __future__ import annotations

import logging
import os
import shutil
import sys

__all__ = ["get_logger", "logger", "LocalFS", "recompute"]


def _rank() -> int:
    from paddle_tpu.distributed.parallel import get_rank
    try:
        return get_rank()
    except Exception:
        return 0


class _RankFilter(logging.Filter):
    def filter(self, record):
        record.rank = _rank()
        return True


def get_logger(level=logging.INFO, name="paddle_tpu.fleet"):
    """Rank-prefixed logger (ref log_util.py:get_logger — the reference
    prefixes every record with the trainer rank)."""
    log = logging.getLogger(name)
    if not log.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(
            "%(asctime)s [rank %(rank)s] %(levelname)s %(message)s"))
        h.addFilter(_RankFilter())
        log.addHandler(h)
        log.propagate = False
    log.setLevel(level)
    return log


logger = get_logger()


class LocalFS:
    """Local filesystem client with the reference's FS interface
    (ref fs.py:LocalFS — ls_dir, mkdirs, rename, delete, upload/download as
    copies, is_file/is_dir/is_exist, touch, mv)."""

    def ls_dir(self, path):
        if not os.path.exists(path):
            return [], []
        dirs, files = [], []
        for e in os.listdir(path):
            (dirs if os.path.isdir(os.path.join(path, e)) else files).append(e)
        return dirs, files

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def rename(self, src, dst):
        os.rename(src, dst)

    mv = rename

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def need_upload_download(self):
        return False

    def upload(self, local, remote):
        shutil.copy(local, remote)

    def download(self, remote, local):
        shutil.copy(remote, local)

    def is_file(self, path):
        return os.path.isfile(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def touch(self, path, exist_ok=True):
        if os.path.exists(path) and not exist_ok:
            raise FileExistsError(path)
        open(path, "a").close()

    def cat(self, path):
        with open(path) as f:
            return f.read()

    def list_dirs(self, path):
        return self.ls_dir(path)[0]


def recompute(function, *args, **kwargs):
    """Re-export of the recompute API at the reference's fleet.utils path."""
    from paddle_tpu.distributed.fleet.recompute import recompute as _rc
    return _rc(function, *args, **kwargs)
