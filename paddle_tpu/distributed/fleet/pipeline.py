"""SPMD pipeline-parallel engine over the 'pp' mesh axis.

Counterpart of the reference's pipeline runtime — 1F1B `forward_backward_pipeline`
(`python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:119`), stage
layers (`parallel_layers/pp_layers.py:209`) and the p2p layer
(`pp_utils/p2p_communication.py:74`) — redesigned for XLA's single-program model:

- every pp rank holds ONE stage's weights (per-stage param trees stacked on a
  leading [pp] axis, sharded over the 'pp' mesh axis);
- a shard_map body runs the GPipe schedule: `n_micro + pp - 1` unrolled steps,
  each computing the local stage on the current micro-batch and handing the
  activation to the next stage with `jax.lax.ppermute` (the send/recv pair the
  reference implements as batched isend/irecv);
- the BACKWARD pipeline falls out of jax.vjp: the transpose of `ppermute` is the
  reversed ring, so the reverse schedule with its p2p traffic is derived, not
  hand-written.

Loss semantics match the reference's accumulate-then-step contract (GPipe ==
1F1B numerically; 1F1B only changes peak memory, which XLA already schedules).
"""
from __future__ import annotations

import contextlib
import time

import jax
import jax.numpy as jnp

from paddle_tpu.framework.jax_compat import shard_map as _shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.observability import metrics


def note_pipeline_dispatch(engine, n_stages, n_micro, n_ticks, t0, dt):
    """Per-call pipeline schedule accounting, shared by both engines.

    The GPipe schedule lives inside ONE XLA program, so per-tick host timers
    cannot exist; what the host observes is the dispatch of the whole
    `n_micro + s_total - 1`-tick schedule. `tick_seconds` divides that wall
    time evenly over the ticks — the per-(stage, microbatch) figure the
    reference reads off its per-micro p2p timeline. Dispatch is async under
    jax: on a first call the figure includes compile; steady-state calls that
    are not immediately consumed may under-report device time (p50 vs max in
    the histogram separates the two regimes)."""
    metrics.counter("pipeline.calls", engine=engine).inc()
    metrics.counter("pipeline.microbatches", engine=engine).inc(n_micro)
    metrics.gauge("pipeline.stages", engine=engine).set(n_stages)
    metrics.histogram("pipeline.dispatch_seconds", engine=engine).observe(dt)
    metrics.histogram("pipeline.tick_seconds", engine=engine).observe(
        dt / max(n_ticks, 1))
    metrics.add_span(f"pipeline.dispatch:{engine}", t0, dt, cat="pipeline")


class _GuardGenerator:
    """Swapped in as the default RNG generator while template layers execute
    inside a raw jax trace (pipeline stage_fn / MoE expert_fn): stateful RNG
    there would write a leaked tracer into the global generator and bake a
    constant mask. Raising turns silent corruption into a clear error."""

    def __init__(self, what):
        self._what = what

    def __getattr__(self, name):
        raise RuntimeError(
            f"stateful RNG (e.g. Dropout) is not supported inside {self._what}"
            " — the template body is traced outside the to_static RNG-threading"
            " machinery. Set dropout to 0 in these blocks (or move the dropout"
            " outside the pipelined/expert region).")


@contextlib.contextmanager
def template_rng_guard(what):
    from paddle_tpu.ops import random as rnd
    prev = rnd._default_generator
    rnd._default_generator = _GuardGenerator(what)
    try:
        yield
    finally:
        rnd._default_generator = prev


@contextlib.contextmanager
def functional_rng(key):
    """Install a functional generator (ops/random.FunctionalGenerator) so
    nn.Dropout works inside pipeline stage / expert bodies: draws fold a
    deterministic per-call counter into ``key`` instead of mutating global
    state (the TPU answer to the reference's RNGStatesTracker,
    `fleet/layers/mpu/random.py:34` — placement-independent by construction)."""
    from paddle_tpu.ops import random as rnd
    prev = rnd._default_generator
    rnd._default_generator = rnd.FunctionalGenerator(key)
    try:
        yield
    finally:
        rnd._default_generator = prev


def stage_rng_key(base_key, logical_stage, micro):
    """The per-(logical stage, microbatch) dropout key. ONE derivation shared
    by the SPMD engine and the serial oracle, so RNG is a function of model
    position — not of how the pipeline is partitioned."""
    import jax.random as jrandom
    return jrandom.fold_in(jrandom.fold_in(base_key, logical_stage), micro)


def spmd_pipeline(stage_fn, n_stages, n_micro, stacked_params, x, mesh,
                  rng_key=None):
    """Pure-jax GPipe over the 'pp' axis — the single-chunk case of
    :func:`spmd_pipeline_interleaved`.

    stage_fn(local_param_arrays, x_micro) -> y_micro  (shape-preserving);
    with ``rng_key`` it is called as stage_fn(params, x_micro, key).
    stacked_params: list of arrays [n_stages, ...] (leading axis = stage id)
    x: [B, ...] full batch; B must divide into n_micro micro-batches.
    Returns [B, ...] outputs of the LAST stage, replicated over 'pp'.
    """
    return spmd_pipeline_interleaved(stage_fn, n_stages, 1, n_micro,
                                     stacked_params, x, mesh,
                                     rng_key=rng_key)


def pipeline_serial_reference(stage_fn, s_total, n_micro, logical_params, x,
                              rng_key=None):
    """Single-device oracle computing EXACTLY the function the SPMD engine
    computes (same microbatching, same `stage_rng_key` derivation) — the
    parity reference for tests and the multichip dryrun.

    logical_params: arrays with leading axis s_total in LOGICAL stage order
    (the engine instead wants rank-major, see spmd_pipeline_interleaved).
    """
    B = x.shape[0]
    mb = B // n_micro
    xm = x.reshape((n_micro, mb) + x.shape[1:])
    outs = []
    for m in range(n_micro):
        h = xm[m]
        for s in range(s_total):
            local = [p[s] for p in logical_params]
            if rng_key is None:
                h = stage_fn(local, h)
            else:
                h = stage_fn(local, h, stage_rng_key(rng_key, s, m))
        outs.append(h)
    return jnp.concatenate(outs, axis=0)


def stack_stage_params(per_stage_param_trees, mesh):
    """[stage][i] -> list of stacked arrays [n_stages, ...] placed on 'pp'.

    per_stage_param_trees: list (one per stage) of equal-length lists of
    jax arrays in matching order/shapes. A source param already carrying a
    NamedSharding (e.g. the mpu layers' 'mp' placements) keeps its spec with
    'pp' prepended, so pipeline and tensor parallelism compose in one mesh.
    """
    n = len(per_stage_param_trees)
    ref0 = per_stage_param_trees[0]
    for s, tree in enumerate(per_stage_param_trees[1:], 1):
        if len(tree) != len(ref0) or any(
                a.shape != b.shape or a.dtype != b.dtype
                for a, b in zip(tree, ref0)):
            raise ValueError(
                f"pipeline stage {s} param tree differs from stage 0 — "
                "SPMD pipelining needs structurally identical stages")
    stacked = []
    for i in range(len(ref0)):
        arr = jnp.stack([per_stage_param_trees[s][i] for s in range(n)])
        src_sh = getattr(ref0[i], "sharding", None)
        if isinstance(src_sh, NamedSharding) and any(
                ax is not None for ax in src_sh.spec):
            spec = P("pp", *src_sh.spec)
        else:
            spec = P("pp", *([None] * (arr.ndim - 1)))
        stacked.append(jax.device_put(arr, NamedSharding(mesh, spec)))
    return stacked


def spmd_pipeline_interleaved(stage_fn, n_stages, n_chunks, n_micro,
                              stacked_params, x, mesh, rng_key=None):
    """Interleaved (virtual-stage) GPipe over the 'pp' axis — the SPMD analog
    of the reference's `PipelineParallelWithInterleave`
    (`meta_parallel/pipeline_parallel.py:463`): each rank owns ``n_chunks``
    non-adjacent model chunks, so the pipeline bubble shrinks by ~1/n_chunks.

    stage_fn(chunk_param_arrays, x_micro) -> y_micro  (shape-preserving);
    with ``rng_key`` it is called as stage_fn(params, x_micro, key) where key
    is `stage_rng_key(rng_key, logical_stage, micro)` — dropout inside stage
    bodies is then deterministic in model position, so the serial oracle
    (:func:`pipeline_serial_reference`) reproduces it bit-for-bit.
    stacked_params: arrays with leading axis n_stages * n_chunks in RANK-MAJOR
    order — index r * n_chunks + c holds the params of LOGICAL stage
    c * n_stages + r (shard_map splits the leading axis contiguously per rank,
    so each rank's local block is its n_chunks chunks in order). Build it as
    ``stacked_logical[[c * n_stages + r for r in range(S) for c in range(V)]]``.
    Returns the final chunk's outputs [B, ...], replicated over 'pp'.
    """
    B = x.shape[0]
    assert B % n_micro == 0, f"batch {B} not divisible into {n_micro} micro"
    mb = B // n_micro
    s_total = n_stages * n_chunks
    xm = x.reshape((n_micro, mb) + x.shape[1:])
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    for p in stacked_params:
        assert p.shape[0] == s_total, (
            f"stacked param leading axis {p.shape[0]} != "
            f"n_stages*n_chunks={s_total}")

    def per_rank(params, xs, *key_data):
        # shard_map's contiguous P('pp') split gives each rank its local
        # [n_chunks, ...] block (rank-major layout, see docstring)
        local = list(params)
        r = jax.lax.axis_index("pp")
        is_first = (r == 0)
        is_last = (r == n_stages - 1)
        base_key = (jax.random.wrap_key_data(key_data[0])
                    if key_data else None)
        carry = jnp.zeros((n_chunks, mb) + xs.shape[2:], xs.dtype)
        ys_hist = []
        total_ticks = n_micro + s_total - 1
        for t in range(total_ticks):
            feed = xs[min(t, n_micro - 1)]
            x0 = jnp.where(is_first, feed, carry[0]) \
                if t < n_micro else carry[0]
            # concatenate, not carry.at[0].set: an in-place update on the
            # big carried buffer creates a full un-aliasable buffer version
            # per unrolled tick in the compiled vjp (measured: ~1 MB/tick
            # fixed temp overhead that erased the pipeline's memory win)
            x_in = (jnp.concatenate([x0[None], carry[1:]], axis=0)
                    if n_chunks > 1 else x0[None])
            # all chunks advance one tick in parallel (independent microbatches)
            if base_key is not None:
                # chunk ci runs LOGICAL stage s = ci*n_stages + r, which at
                # tick t holds microbatch m = t - s (clipped: out-of-range
                # ticks compute garbage that never reaches the output)
                s_ids = jnp.arange(n_chunks) * n_stages + r
                m_ids = jnp.clip(t - s_ids, 0, n_micro - 1)
                keys = jax.vmap(
                    lambda s, m: stage_rng_key(base_key, s, m))(s_ids, m_ids)
                y = _vmap_chunks(stage_fn, local, x_in, keys)
            else:
                y = _vmap_chunks(stage_fn, local, x_in)
            # microbatch m leaves the last chunk of the last rank at
            # t = m + s_total - 1; stash this tick's output instead of
            # updating an [n_micro, ...] buffer in place (aliasing, above)
            ys_hist.append(y)
            if t < total_ticks - 1:
                moved = jax.lax.ppermute(y, "pp", perm)
                # the wrap-around from the last rank enters the NEXT chunk on
                # rank 0; other ranks keep chunk alignment
                rolled = jnp.roll(moved, 1, axis=0)
                carry = jnp.where(is_first, rolled, moved)
        outs = jnp.stack([ys_hist[m + s_total - 1][-1]
                          for m in range(n_micro)])
        return jax.lax.psum(
            jnp.where(is_last, outs, jnp.zeros_like(outs)), "pp")

    def _vmap_chunks(fn, local, x_in, keys=None):
        # vmap over the chunk axis of the local params and carries
        if keys is None:
            return jax.vmap(lambda *args: fn(list(args[:-1]), args[-1]))(
                *local, x_in)
        return jax.vmap(
            lambda *args: fn(list(args[:-2]), args[-2], args[-1]))(
            *local, x_in, keys)

    extra = ()
    extra_specs = ()
    if rng_key is not None:
        # raw uint32 key data crosses the shard_map boundary (replicated);
        # typed keys are rewrapped inside per_rank
        extra = (jax.random.key_data(rng_key),)
        extra_specs = (P(),)
    f = _shard_map(
        per_rank, mesh=mesh,
        in_specs=(tuple(P("pp") for _ in stacked_params), P()) + extra_specs,
        out_specs=P(), axis_names={"pp"},
        # check_vma must stay off here: the stage bodies run
        # with_sharding_constraint on AUTO axes (dp/mp/sp), and jax's
        # vma checker rejects auto-typed axes inside a manual region
        # (ValueError: axes in vma should be Manual). The ring/ulysses
        # shard_maps, which constrain nothing, run with check_vma=True.
        check_vma=False)
    t0 = time.perf_counter()
    outs = f(tuple(stacked_params), xm, *extra)
    note_pipeline_dispatch("spmd", n_stages, n_micro,
                           n_micro + s_total - 1, t0,
                           time.perf_counter() - t0)
    return outs.reshape((B,) + outs.shape[2:])
