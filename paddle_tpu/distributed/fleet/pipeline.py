"""SPMD pipeline-parallel engine over the 'pp' mesh axis.

Counterpart of the reference's pipeline runtime — 1F1B `forward_backward_pipeline`
(`python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:119`), stage
layers (`parallel_layers/pp_layers.py:209`) and the p2p layer
(`pp_utils/p2p_communication.py:74`) — redesigned for XLA's single-program model:

- every pp rank holds ONE stage's weights (per-stage param trees stacked on a
  leading [pp] axis, sharded over the 'pp' mesh axis);
- a shard_map body runs the GPipe schedule: `n_micro + pp - 1` unrolled steps,
  each computing the local stage on the current micro-batch and handing the
  activation to the next stage with `jax.lax.ppermute` (the send/recv pair the
  reference implements as batched isend/irecv);
- the BACKWARD pipeline falls out of jax.vjp: the transpose of `ppermute` is the
  reversed ring, so the reverse schedule with its p2p traffic is derived, not
  hand-written.

Loss semantics match the reference's accumulate-then-step contract (GPipe ==
1F1B numerically; 1F1B only changes peak memory, which XLA already schedules).
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


class _GuardGenerator:
    """Swapped in as the default RNG generator while template layers execute
    inside a raw jax trace (pipeline stage_fn / MoE expert_fn): stateful RNG
    there would write a leaked tracer into the global generator and bake a
    constant mask. Raising turns silent corruption into a clear error."""

    def __init__(self, what):
        self._what = what

    def __getattr__(self, name):
        raise RuntimeError(
            f"stateful RNG (e.g. Dropout) is not supported inside {self._what}"
            " — the template body is traced outside the to_static RNG-threading"
            " machinery. Set dropout to 0 in these blocks (or move the dropout"
            " outside the pipelined/expert region).")


@contextlib.contextmanager
def template_rng_guard(what):
    from paddle_tpu.ops import random as rnd
    prev = rnd._default_generator
    rnd._default_generator = _GuardGenerator(what)
    try:
        yield
    finally:
        rnd._default_generator = prev


def spmd_pipeline(stage_fn, n_stages, n_micro, stacked_params, x, mesh):
    """Pure-jax GPipe over the 'pp' axis.

    stage_fn(local_param_arrays, x_micro) -> y_micro  (shape-preserving)
    stacked_params: list of arrays [n_stages, ...] (leading axis = stage id)
    x: [B, ...] full batch; B must divide into n_micro micro-batches.
    Returns [B, ...] outputs of the LAST stage, replicated over 'pp'.
    """
    B = x.shape[0]
    assert B % n_micro == 0, f"batch {B} not divisible into {n_micro} micro"
    mb = B // n_micro
    xm = x.reshape((n_micro, mb) + x.shape[1:])
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def per_rank(params, xs):
        local = [p[0] for p in params]          # [1, ...] slice -> this stage
        r = jax.lax.axis_index("pp")
        is_first = (r == 0)
        is_last = (r == n_stages - 1)
        carry = jnp.zeros(xs.shape[1:], xs.dtype)
        outs = jnp.zeros_like(xs)
        for t in range(n_micro + n_stages - 1):
            feed = xs[min(t, n_micro - 1)]
            x_in = jnp.where(is_first, feed, carry) if t < n_micro else carry
            y = stage_fn(local, x_in)
            m = t - (n_stages - 1)
            if 0 <= m < n_micro:
                outs = outs.at[m].set(jnp.where(is_last, y, outs[m]))
            if t < n_micro + n_stages - 2:
                carry = jax.lax.ppermute(y, "pp", perm)
        # replicate the last stage's results onto every pp rank
        return jax.lax.psum(
            jnp.where(is_last, outs, jnp.zeros_like(outs)), "pp")

    f = jax.shard_map(
        per_rank, mesh=mesh,
        in_specs=(tuple(P("pp") for _ in stacked_params), P()),
        out_specs=P(), axis_names={"pp"}, check_vma=False)
    outs = f(tuple(stacked_params), xm)
    return outs.reshape((B,) + outs.shape[2:])


def stack_stage_params(per_stage_param_trees, mesh):
    """[stage][i] -> list of stacked arrays [n_stages, ...] placed on 'pp'.

    per_stage_param_trees: list (one per stage) of equal-length lists of
    jax arrays in matching order/shapes.
    """
    n = len(per_stage_param_trees)
    ref0 = per_stage_param_trees[0]
    for s, tree in enumerate(per_stage_param_trees[1:], 1):
        if len(tree) != len(ref0) or any(
                a.shape != b.shape or a.dtype != b.dtype
                for a, b in zip(tree, ref0)):
            raise ValueError(
                f"pipeline stage {s} param tree differs from stage 0 — "
                "SPMD pipelining needs structurally identical stages")
    stacked = []
    for i in range(len(ref0)):
        arr = jnp.stack([per_stage_param_trees[s][i] for s in range(n)])
        spec = P("pp", *([None] * (arr.ndim - 1)))
        stacked.append(jax.device_put(arr, NamedSharding(mesh, spec)))
    return stacked
