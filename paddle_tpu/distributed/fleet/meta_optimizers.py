"""Fleet meta-optimizers: gradient merge, LocalSGD, DGC, FP16-allreduce.

Rebuild of the reference's meta-optimizer stack
(`python/paddle/distributed/fleet/meta_optimizers/{gradient_merge_optimizer,
localsgd_optimizer,dgc_optimizer,fp16_allreduce_optimizer}.py`). The reference
rewrites static Programs; here each is an optimizer wrapper an eager/captured
step composes around the inner optimizer, selected by `DistributedStrategy`
flags through `fleet.distributed_optimizer` exactly like the reference's
`_prepare_meta_optimizers`.

TPU mapping notes
- Gradient merge: accumulate k micro-steps in f32 buffers, apply on the k-th
  (ref gradient_merge_optimizer.py; the GradientMergePass's cond-block becomes
  a host-side counter — under `to_static` capture the whole merged step is one
  compiled program either way).
- LocalSGD: every rank steps locally, parameters are averaged across the data
  axis every k steps (ref localsgd_optimizer.py:BEGIN_STEP/avg loop).
- DGC: top-k gradient sparsification with momentum correction + local error
  feedback (ref dgc_optimizer.py + `operators/dgc_op.cc`). In-graph DP under
  GSPMD already allreduces dense grads optimally over ICI, so the win here is
  the multi-process (DCN) path: sparsified grads travel as (indices, values)
  through the eager collective layer.
- FP16 allreduce: grads cast to bf16/f16 around the cross-rank reduce
  (ref fp16_allreduce_optimizer.py); on TPU bf16 is the native wire format.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.selected_rows import SelectedRows
from paddle_tpu.core.tensor import Tensor


class _MetaOptimizerBase:
    """Delegates everything to the inner optimizer unless overridden."""

    def __init__(self, inner_opt):
        self._inner_opt = inner_opt

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    @property
    def inner_opt(self):
        return self._inner_opt

    def clear_grad(self, set_to_zero=False):
        self._inner_opt.clear_grad(set_to_zero)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        # route through self.step() so the meta behavior applies (the inner
        # optimizer's bound minimize would bypass it)
        loss.backward()
        self.step()
        return None, [(p, p.grad)
                      for p in self._inner_opt._parameter_list]

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, state):
        self._inner_opt.set_state_dict(state)


class GradientMergeOptimizer(_MetaOptimizerBase):
    """Accumulate gradients for ``k_steps`` before applying
    (ref meta_optimizers/gradient_merge_optimizer.py)."""

    def __init__(self, inner_opt, k_steps=1, avg=True):
        super().__init__(inner_opt)
        self.k_steps = int(k_steps)
        self.avg = avg
        self._acc = {}
        self._sparse_acc = {}
        self._count = 0

    def step(self):
        self._count += 1
        params = self._inner_opt._parameter_list
        for i, p in enumerate(params):
            if p.grad is None:
                continue
            if isinstance(p.grad, SelectedRows):
                # buffer sparse grads too (clear_grad would drop them)
                prev = self._sparse_acc.get(i)
                self._sparse_acc[i] = p.grad if prev is None else \
                    prev.accumulate(p.grad)
                continue
            g = p.grad._data.astype(jnp.float32)
            self._acc[i] = g if i not in self._acc else self._acc[i] + g
        if self._count < self.k_steps:
            # swallow the inner step; grads are buffered
            self._inner_opt.clear_grad()
            return
        scale = 1.0 / self.k_steps if self.avg else 1.0
        for i, p in enumerate(params):
            if i in self._acc:
                p._grad = Tensor((self._acc[i] * scale).astype(p.dtype),
                                 _internal=True)
            elif i in self._sparse_acc:
                sr = self._sparse_acc[i]
                p._grad = SelectedRows(sr.rows, sr.values * scale, sr.height)
        self._inner_opt.step()
        self._acc = {}
        self._sparse_acc = {}
        self._count = 0


class LocalSGDOptimizer(_MetaOptimizerBase):
    """Step locally, average parameters across workers every ``k_steps``
    (ref meta_optimizers/localsgd_optimizer.py)."""

    def __init__(self, inner_opt, k_steps=1, begin_step=1, group=None):
        super().__init__(inner_opt)
        self.k_steps = int(k_steps)
        self.begin_step = int(begin_step)
        self._group = group
        self._step_id = 0

    def _average_params(self):
        from paddle_tpu.distributed import collective
        from paddle_tpu.distributed.parallel import get_world_size
        n = get_world_size(self._group)
        if n <= 1:
            return
        for p in self._inner_opt._parameter_list:
            collective.all_reduce(p, op=collective.ReduceOp.SUM,
                                  group=self._group)
            p._write((p._data / n).astype(p.dtype))

    def step(self):
        self._inner_opt.step()
        self._step_id += 1
        if (self._step_id >= self.begin_step
                and self._step_id % self.k_steps == 0):
            self._average_params()


class DGCOptimizer(_MetaOptimizerBase):
    """Deep Gradient Compression: momentum correction + top-k sparsification
    with local error feedback (ref meta_optimizers/dgc_optimizer.py,
    `paddle/fluid/operators/dgc_op.cc`; Lin et al., 2018).

    Before the inner step, each gradient is replaced by its top-``sparsity``
    fraction (by magnitude) of the *velocity* (momentum-corrected accumulated
    gradient); the untransmitted remainder stays in the local error-feedback
    buffers. Ramp-up: before ``rampup_begin_step`` gradients pass through
    untouched.
    """

    def __init__(self, inner_opt, rampup_begin_step=0, momentum=0.9,
                 sparsity=0.999):
        super().__init__(inner_opt)
        self.rampup_begin_step = int(rampup_begin_step)
        self.momentum = float(momentum)
        self.sparsity = float(sparsity)
        self._u = {}   # velocity (momentum correction)
        self._v = {}   # error-feedback accumulator
        self._step_id = 0

    @staticmethod
    def _topk_mask(flat, k):
        # smallest |g| zeroed; k = number of entries KEPT
        if k >= flat.shape[0]:
            return jnp.ones_like(flat, dtype=bool)
        thresh = jnp.sort(jnp.abs(flat))[-k]
        return jnp.abs(flat) >= thresh

    def _compress(self, i, g):
        u = self._u.get(i)
        v = self._v.get(i)
        u = g if u is None else self.momentum * u + g
        v = u if v is None else v + u
        flat = v.reshape(-1)
        keep = max(1, int(round(flat.shape[0] * (1.0 - self.sparsity))))
        mask = self._topk_mask(flat, keep).reshape(v.shape)
        sent = jnp.where(mask, v, 0)
        # error feedback: masked-out residue stays local (dgc_op.cc semantics:
        # U/V cleared where transmitted)
        self._u[i] = jnp.where(mask, 0, u)
        self._v[i] = jnp.where(mask, 0, v)
        return sent

    def step(self):
        self._step_id += 1
        if self._step_id > self.rampup_begin_step:
            for i, p in enumerate(self._inner_opt._parameter_list):
                if p.grad is None or isinstance(p.grad, SelectedRows):
                    continue   # sparse grads are already compressed by nature
                g = p.grad._data.astype(jnp.float32)
                p._grad = Tensor(self._compress(i, g).astype(p.dtype),
                                 _internal=True)
        self._inner_opt.step()


class FP16AllreduceOptimizer(_MetaOptimizerBase):
    """Cast gradients to a low-precision wire format around the cross-rank
    reduce (ref meta_optimizers/fp16_allreduce_optimizer.py). On TPU the wire
    dtype defaults to bf16 (no loss-scale needed, matching the amp design)."""

    def __init__(self, inner_opt, wire_dtype="bfloat16", group=None):
        super().__init__(inner_opt)
        self.wire_dtype = jnp.bfloat16 if wire_dtype == "bfloat16" else \
            jnp.float16
        self._group = group

    def step(self):
        from paddle_tpu.distributed import collective
        from paddle_tpu.distributed.parallel import get_world_size
        world = get_world_size(self._group)
        if world > 1:   # the cast only buys anything on the wire
            for p in self._inner_opt._parameter_list:
                if p.grad is None or isinstance(p.grad, SelectedRows):
                    continue
                g16 = p.grad._data.astype(self.wire_dtype)
                t = Tensor(g16, _internal=True)
                collective.all_reduce(t, group=self._group)
                p._grad = Tensor((t._data / world).astype(jnp.float32),
                                 _internal=True)
        self._inner_opt.step()


def apply_meta_optimizers(optimizer, strategy, hcg=None):
    """Compose meta-optimizers by strategy flags, mirroring the reference's
    `_prepare_meta_optimizers` selection (fleet.py)."""
    opt = optimizer
    dp_group = None
    if hcg is not None:
        try:
            dp_group = hcg.get_data_parallel_group()
        except Exception:
            dp_group = None
    if getattr(strategy, "dgc", False):
        cfg = getattr(strategy, "dgc_configs", {}) or {}
        opt = DGCOptimizer(opt,
                           rampup_begin_step=cfg.get("rampup_begin_step", 0),
                           momentum=cfg.get("momentum", 0.9),
                           sparsity=cfg.get("sparsity", 0.999))
    if getattr(strategy, "fp16_allreduce", False):
        opt = FP16AllreduceOptimizer(opt, group=dp_group)
    if getattr(strategy, "localsgd", False):
        cfg = getattr(strategy, "localsgd_configs", {}) or {}
        opt = LocalSGDOptimizer(opt, k_steps=cfg.get("k_steps", 1),
                                begin_step=cfg.get("begin_step", 1),
                                group=dp_group)
    if getattr(strategy, "gradient_merge", False):
        cfg = getattr(strategy, "gradient_merge_configs", {}) or {}
        opt = GradientMergeOptimizer(opt, k_steps=cfg.get("k_steps", 1),
                                     avg=cfg.get("avg", True))
    return opt
