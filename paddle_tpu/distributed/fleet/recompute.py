"""Activation recompute (ref: `fleet/recompute/recompute.py:223` RecomputeFunction
PyLayer with RNG-state replay; api :385, sequential :496).

TPU-native: `jax.checkpoint` (rematerialization) applied to the region's primal —
XLA recomputes the forward during backward instead of saving activations. RNG
determinism under replay is handled by passing a PRNG key as an explicit input to
the checkpointed region and running a scoped generator from it, so the remat replay
sees the identical key (the reference must save/restore CUDA RNG state by hand at
`recompute.py:129-151`).
"""
from __future__ import annotations

import jax

from paddle_tpu.core.autograd import apply, no_grad
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.common import ensure_tensor


def _collect_layer_state(layer):
    """Params + float buffers of a Layer — the non-arg tensors the region reads."""
    extras = list(layer.parameters())
    for b in layer.buffers():
        if b is not None:
            extras.append(b)
    return extras


def _probe_extras(function, tensor_args, call_args_builder, kwargs):
    """Hook-based discovery for non-Layer callables: run the function once under
    abstract evaluation with read/write hooks; restore every written tensor."""
    from paddle_tpu.core import tensor as tensor_mod
    extras: dict[int, Tensor] = {}
    written: dict[int, tuple] = {}

    def read_hook(t):
        if id(t) not in extras and all(t is not ta for ta in tensor_args):
            extras[id(t)] = t

    def write_hook(t):
        if id(t) not in written:
            written[id(t)] = (t, t._data)

    prev = tensor_mod.set_capture_hooks(read_hook, write_hook)
    try:
        with no_grad():
            jax.eval_shape(lambda *arrs: [
                o._data for o in _aslist(function(*call_args_builder(arrs),
                                                  **kwargs))],
                *[t._data for t in tensor_args])
    except Exception:
        pass
    finally:
        tensor_mod.set_capture_hooks(*prev)
        for t, old in written.values():
            t._data = old
    return [t for t in extras.values()]


def _aslist(out):
    return list(out) if isinstance(out, (tuple, list)) else [out]


def recompute(function, *args, **kwargs):
    """Run ``function(*args)`` with rematerialized backward."""
    kwargs.pop("preserve_rng_state", None)
    kwargs.pop("use_reentrant", None)
    from paddle_tpu.nn.layer import Layer
    from paddle_tpu.ops import random as rnd

    tensor_args = []
    spec = []
    for a in args:
        if isinstance(a, Tensor):
            spec.append(("t", len(tensor_args)))
            tensor_args.append(a)
        else:
            spec.append(("c", a))

    def build_call_args(arrs):
        out = []
        for kind, v in spec:
            if kind == "t":
                out.append(Tensor(arrs[v], stop_gradient=False, _internal=True))
            else:
                out.append(v)
        return out

    if isinstance(function, Layer):
        extra_list = _collect_layer_state(function)
    else:
        extra_list = _probe_extras(function, tensor_args, build_call_args, kwargs)

    # advance the global generator ONCE, outside the region; the region runs a
    # scoped generator seeded from that key, passed as a real input so the remat
    # replay and any outer capture see a consistent value.
    key_data = rnd.default_generator().next_key()
    key_t = Tensor(jax.random.key_data(key_data), _internal=True)

    n_main = len(tensor_args)
    n_extra = len(extra_list)

    @jax.checkpoint
    def prim(*arrs):
        arrs_main = arrs[:n_main]
        arrs_extra = arrs[n_main:n_main + n_extra]
        key_arr = arrs[n_main + n_extra]
        saved = [(t, t._data) for t in extra_list]
        gen = rnd.Generator.__new__(rnd.Generator)
        gen._state = Tensor(key_arr, _internal=True)
        gen._seed = 0
        prev_gen = rnd._default_generator
        rnd._default_generator = gen
        try:
            for t, a in zip(extra_list, arrs_extra):
                t._data = a
            # inner tape recording is pointless: the outer jax.vjp of this prim
            # differentiates the whole region functionally
            with no_grad():
                out = function(*build_call_args(arrs_main), **kwargs)
            outs = [o._data for o in _aslist(out)]
            return tuple(outs) if isinstance(out, (tuple, list)) else outs[0]
        finally:
            rnd._default_generator = prev_gen
            for t, a in saved:
                t._data = a

    return apply(prim, *tensor_args, *extra_list, key_t, op_name="recompute")


def recompute_sequential(ctx, functions, *args, **kwargs):
    """ref `recompute.py:496` — recompute a Sequential in segments."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    n = len(layers)
    seg_size = max(n // max(segments, 1), 1)

    def run_segment(lo, hi):
        def seg_fn(x):
            for l in layers[lo:hi]:
                x = l(x)
            return x
        return seg_fn

    x = args[0] if len(args) == 1 else args
    for lo in range(0, n, seg_size):
        hi = min(lo + seg_size, n)
        x = recompute(run_segment(lo, hi), x, **kwargs)
    return x


def recompute_hybrid(ctx, function, *args, **kwargs):
    """ref `recompute_hybrid.py:69` — in the reference, saved activations are
    additionally partitioned across the mp group; with remat there are no saved
    activations to partition, so this is recompute (kept for API parity)."""
    return recompute(function, *args, **kwargs)
