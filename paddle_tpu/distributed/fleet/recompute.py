"""Activation recompute (ref: `fleet/recompute/recompute.py:223` RecomputeFunction
PyLayer with RNG-state replay; api :385, sequential :496).

TPU-native: `jax.checkpoint` (rematerialization) applied to the op's primal inside
the tape — XLA recomputes the forward in backward instead of saving activations.
RNG determinism comes free: the PRNG key is captured functionally, so replay is
exact (the reference must save/restore CUDA RNG state by hand).
"""
from __future__ import annotations

import jax

from paddle_tpu.core.autograd import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.common import ensure_tensor


def recompute(function, *args, **kwargs):
    """Run `function(*args)` with rematerialized backward."""
    preserve = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)
    tensor_args = []
    spec = []
    for a in args:
        if isinstance(a, Tensor):
            spec.append(("t", len(tensor_args)))
            tensor_args.append(a)
        else:
            spec.append(("c", a))

    # capture layer params read inside `function` as explicit tensor inputs so
    # the checkpointed region differentiates w.r.t. them too
    from paddle_tpu.core import tensor as tensor_mod
    extra: dict[int, Tensor] = {}

    def read_hook(t):
        if id(t) not in extra and all(t is not ta for ta in tensor_args):
            extra[id(t)] = t

    def run(arrs_main, arrs_extra, extra_list):
        saved = [(t, t._data) for t in extra_list]
        try:
            for t, a in zip(extra_list, arrs_extra):
                t._data = a
            call_args = []
            for kind, v in spec:
                if kind == "t":
                    call_args.append(Tensor(arrs_main[v], stop_gradient=False,
                                            _internal=True))
                else:
                    call_args.append(v)
            out = function(*call_args, **kwargs)
            multi = isinstance(out, (tuple, list))
            outs = [o._data for o in (out if multi else [out])]
            return tuple(outs) if multi else outs[0]
        finally:
            for t, a in saved:
                t._data = a

    # discover extra params with one hooked dry trace via jax.eval_shape
    prev = tensor_mod.set_capture_hooks(read_hook, None)
    try:
        jax.eval_shape(
            lambda *arrs: run(arrs, [], []),
            *[t._data for t in tensor_args])
    except Exception:
        pass
    finally:
        tensor_mod.set_capture_hooks(*prev)

    extra_list = list(extra.values())
    n_main = len(tensor_args)

    @jax.checkpoint
    def prim(*arrs):
        return run(arrs[:n_main], arrs[n_main:], extra_list)

    return apply(prim, *tensor_args, *extra_list, op_name="recompute")


def recompute_sequential(ctx, functions, *args, **kwargs):
    """ref `recompute.py:496` — recompute a Sequential in segments."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    from paddle_tpu.nn.layers.container import Sequential
    if isinstance(functions, Sequential):
        layers = list(functions)
    else:
        layers = list(functions)
    n = len(layers)
    seg_size = max(n // max(segments, 1), 1)
    out = args[0] if len(args) == 1 else args

    def run_segment(lo, hi):
        def seg_fn(x):
            for l in layers[lo:hi]:
                x = l(x)
            return x
        return seg_fn

    x = out
    for lo in range(0, n, seg_size):
        hi = min(lo + seg_size, n)
        x = recompute(run_segment(lo, hi), x, **kwargs)
    return x


def recompute_hybrid(ctx, function, *args, **kwargs):
    """ref `recompute_hybrid.py:69` — in the reference, saved activations are
    additionally partitioned across the mp group; with remat there are no saved
    activations to partition, so this is recompute (kept for API parity)."""
    return recompute(function, *args, **kwargs)
