"""Heterogeneous SPMD pipeline engine: arbitrary per-stage graphs + buffers.

Counterpart of the reference's general pipeline — `SegmentLayers`
(`python/paddle/distributed/fleet/meta_parallel/parallel_layers/pp_layers.py:93`)
segments ANY layer list (uniform / param-count / manual) and each stage runs its
own sub-graph (`pp_layers.py:209`), including BN layers with running stats.
The homogeneous engine (`fleet/pipeline.py`) requires structurally identical,
buffer-free stages; this module removes both restrictions, TPU-style:

- Each stage's parameter tree is FLATTENED into per-dtype BUCKET vectors
  (one flat vector per distinct leaf dtype), each padded to the widest stage
  and stacked into a [pp, len] array sharded over 'pp' — so every rank holds
  exactly one stage's weights (1/pp of the model) even when stages differ
  structurally. bf16 leaves ride a bf16 bucket (no f32 upcast tax: round 4's
  single-f32-carrier design doubled HBM for bf16 weights and ICI for bf16
  boundaries — r4 verdict weak #3), and integer leaves ride native integer
  buckets (exact — the old 2^24 mantissa limit is gone). Buffers (BN running
  stats) get the same packing and ride the schedule as per-rank state,
  updated only on valid ticks.
- Activations crossing stage boundaries are packed into fixed-size per-dtype
  buckets (padded to the widest boundary), so `lax.ppermute` can hand them to
  the next stage even when boundary shapes differ (a ResNet's stage cut
  changes [B,C,H,W] between stages; the reference's p2p layer solves this
  with a tensor-meta handshake, `pp_utils/p2p_communication.py:74-154`).
- Inside the shard_map body, `lax.switch(axis_index('pp'), branches)` selects
  the rank's stage sub-graph; XLA compiles all branches into one SPMD program.
  The backward pipeline (reversed ring + branch transposes) falls out of vjp.

``CARRIER_DTYPE`` is an optional FLOAT promotion override: None (default)
keeps every leaf's native dtype; tests chasing exact parity at ResNet depth
set float64 so float leaves are carried (and therefore reduced) in f64.

Design note — switch compile scaling and interleave (r4 verdict weak #4 /
missing #1). ``lax.switch`` over all stage bodies compiles every stage's
graph on every rank: compile time and code size scale O(pp x model). This
is INHERENT to single-controller SPMD with structurally distinct per-rank
graphs: shard_map traces ONE body for all ranks, so per-rank programs can
only differ through traced branching; a "branch-pruned" per-rank closure
would require per-rank executables, i.e. multi-controller deployment (one
process per host compiling only its stages — supported by jax.distributed
but a different execution model, not a drop-in). Mitigations that hold
today: (a) heterogeneous STAGES are few even when models are big — the
typical cut is embedding | uniform blocks | head, and the uniform middle
should use the homogeneous engine (stacked params, one stage body, real
interleave) via `seg_method="uniform"`; (b) XLA CSEs identical sub-graphs
across branches, so near-identical stages cost far less than pp full
models. Interleaved VIRTUAL stages on hetero stages would multiply the
switch count per tick by n_chunks on top of this (V switches x S*V
branches) for a bubble win the homogeneous engine already provides where
interleave matters (deep uniform stacks) — so hetero + num_virtual_
pipeline_stages>1 stays a loud NotImplementedError rather than a slow
surprise.
"""
from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.framework.jax_compat import shard_map as _shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.fleet.pipeline import (
    functional_rng, note_pipeline_dispatch, stage_rng_key,
    template_rng_guard)


# Optional float-leaf promotion (None = native dtypes, exact per-dtype
# packing). float64 gives bit-chasing tests an f64 compute carrier.
CARRIER_DTYPE = None


def _nelems(shape):
    return int(np.prod(shape)) if len(shape) else 1


def carrier_of(dt):
    """Bucket dtype for a leaf dtype: native, unless the leaf is floating
    and a CARRIER_DTYPE promotion is set."""
    dt = jnp.dtype(dt)
    if CARRIER_DTYPE is not None and jnp.issubdtype(dt, jnp.floating):
        return jnp.dtype(CARRIER_DTYPE)
    return dt


def _key(dt):
    return str(jnp.dtype(dt))


def leaf_metas(arrays):
    return [(tuple(a.shape), jnp.result_type(a.dtype)) for a in arrays]


def bucket_sizes(metas):
    """dict bucket-key -> total element count for these leaves."""
    sizes = {}
    for shape, dt in metas:
        k = _key(carrier_of(dt))
        sizes[k] = sizes.get(k, 0) + _nelems(shape)
    return sizes


def bucket_layout(metas):
    """Per-leaf (bucket-key, offset-within-bucket) in pack order."""
    layout, sizes = [], {}
    for shape, dt in metas:
        k = _key(carrier_of(dt))
        off = sizes.get(k, 0)
        layout.append((k, off))
        sizes[k] = off + _nelems(shape)
    return layout


def merge_lengths(all_sizes):
    """Union-max of per-stage bucket sizes -> shared padded lengths (every
    stage's pack must have the same dict structure for stacking/carrying)."""
    out = {}
    for sizes in all_sizes:
        for k, n in sizes.items():
            out[k] = max(out.get(k, 1), n)
    return out or {"float32": 1}


def pack_buckets(arrays, metas, lengths):
    """Flatten+concat leaves into per-dtype bucket vectors zero-padded to
    ``lengths`` (dict key -> padded length). Buckets absent from these
    leaves are emitted as zeros so every stage shares one structure."""
    by = {}
    for a, (shape, dt) in zip(arrays, metas):
        k = _key(carrier_of(dt))
        by.setdefault(k, []).append(jnp.ravel(a).astype(carrier_of(dt)))
    out = {}
    for k, length in lengths.items():
        parts = by.get(k, [])
        flat = (jnp.concatenate(parts) if parts
                else jnp.zeros((0,), jnp.dtype(k)))
        pad = length - flat.shape[0]
        out[k] = jnp.pad(flat, (0, pad)) if pad else flat
    return out


def unpack_buckets(bdict, metas):
    """Inverse of pack_buckets for the valid prefixes described by metas."""
    out, offs = [], {}
    for shape, dt in metas:
        k = _key(carrier_of(dt))
        off = offs.get(k, 0)
        n = _nelems(shape)
        out.append(bdict[k][off:off + n].reshape(shape).astype(dt))
        offs[k] = off + n
    return out


def tmap(f, *trees):
    return jax.tree.map(f, *trees)


def spmd_pipeline_hetero(stage_fns, n_stages, n_micro, packed_params,
                         packed_bufs, xm_flat, out_sizes, mesh, rng_key=None):
    """GPipe schedule over heterogeneous stages.

    stage_fns: per-stage ``fn(param_buckets, buf_buckets, x_buckets[, key])
    -> (y_buckets, new_buf_buckets)``; branches agree on bucket structure
    (they do, by shared padded lengths).
    packed_params / packed_bufs: dict key -> [n_stages, len] (row s = stage s).
    xm_flat: dict key -> [n_micro, act_len_k] — stage-0 inputs per microbatch.
    out_sizes: dict key -> valid prefix of the final stage's output buckets.
    Returns (outs dict key -> [n_micro, out_n_k] replicated,
             new_bufs dict key -> [n_stages, len]).
    """
    act_lens = {k: v.shape[1] for k, v in xm_flat.items()}
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def per_rank(params, bufs, xs, *key_data):
        p = tmap(lambda a: a[0], params)   # local [1, len] block -> [len]
        buf = tmap(lambda a: a[0], bufs)
        r = jax.lax.axis_index("pp")
        is_first = (r == 0)
        is_last = (r == n_stages - 1)
        base_key = (jax.random.wrap_key_data(key_data[0])
                    if key_data else None)
        carry = {k: jnp.zeros((n,), jnp.dtype(k))
                 for k, n in act_lens.items()}
        ys_hist = []
        total_ticks = n_micro + n_stages - 1
        for t in range(total_ticks):
            feed = tmap(lambda a: a[min(t, n_micro - 1)], xs)
            x0 = (tmap(lambda f, c: jnp.where(is_first, f, c), feed, carry)
                  if t < n_micro else carry)
            m_id = jnp.clip(t - r, 0, n_micro - 1)
            if base_key is not None:
                key = stage_rng_key(base_key, r, m_id)
                branches = [
                    (lambda pp_, bb_, xx_, kk_, _f=f: _f(pp_, bb_, xx_, kk_))
                    for f in stage_fns]
                y, buf_new = jax.lax.switch(r, branches, p, buf, x0, key)
            else:
                branches = [
                    (lambda pp_, bb_, xx_, _f=f: _f(pp_, bb_, xx_))
                    for f in stage_fns]
                y, buf_new = jax.lax.switch(r, branches, p, buf, x0)
            # buffer updates (BN running stats) only land on ticks where this
            # rank held a real microbatch — warmup/drain garbage is masked
            valid = (t - r >= 0) & (t - r < n_micro)
            buf = tmap(lambda nb, ob: jnp.where(valid, nb, ob), buf_new, buf)
            # stash per-tick outputs; stacking at the end avoids the
            # per-tick in-place buffer versions that defeated XLA's
            # aliasing in the homogeneous engine (see fleet/pipeline.py)
            ys_hist.append(y)
            if t < total_ticks - 1:
                carry = tmap(lambda a: jax.lax.ppermute(a, "pp", perm), y)
        outs = {k: jnp.stack([ys_hist[m + n_stages - 1][k][:out_sizes[k]]
                              for m in range(n_micro)])
                for k in out_sizes}

        def psum_from_last(o):
            # broadcast-from-last-rank via masked psum. Sub-f32 floats are
            # reduced in f32: XLA CPU's all-reduce emitter aborts on bf16
            # ('Invalid binary instruction opcode copy') when composed with
            # switch+ppermute in one shard_map program; exactly one rank is
            # nonzero so the upcast round-trips losslessly.
            masked = jnp.where(is_last, o, jnp.zeros_like(o))
            if jnp.issubdtype(o.dtype, jnp.floating) and \
                    jnp.dtype(o.dtype).itemsize < 4:
                return jax.lax.psum(masked.astype(jnp.float32),
                                    "pp").astype(o.dtype)
            return jax.lax.psum(masked, "pp")

        outs = tmap(psum_from_last, outs)
        return outs, tmap(lambda a: a[None], buf)

    extra, extra_specs = (), ()
    if rng_key is not None:
        extra = (jax.random.key_data(rng_key),)
        extra_specs = (P(),)
    f = _shard_map(
        per_rank, mesh=mesh,
        in_specs=(tmap(lambda _: P("pp", None), packed_params),
                  tmap(lambda _: P("pp", None), packed_bufs),
                  tmap(lambda _: P(), xm_flat)) + extra_specs,
        out_specs=({k: P() for k in out_sizes},
                   tmap(lambda _: P("pp", None), packed_bufs)),
        axis_names={"pp"},
        # see fleet/pipeline.py: stage bodies may run with_sharding_constraint
        # on AUTO axes, which the vma checker rejects inside manual regions
        check_vma=False)
    t0 = time.perf_counter()
    out = f(packed_params, packed_bufs, xm_flat, *extra)
    note_pipeline_dispatch("hetero", n_stages, n_micro,
                           n_micro + n_stages - 1, t0,
                           time.perf_counter() - t0)
    return out


def hetero_serial_reference(stage_fns, n_stages, n_micro, packed_params,
                            packed_bufs, xm_flat, out_sizes, rng_key=None):
    """Single-device oracle: same microbatching, same packing, same
    `stage_rng_key` derivation, same per-stage buffer update order —
    the parity reference for tests (cf. pipeline_serial_reference)."""
    bufs = [tmap(lambda a: a[s], packed_bufs)  # noqa: B023
            for s in range(n_stages)]
    outs = []
    for m in range(n_micro):
        h = tmap(lambda a: a[m], xm_flat)
        for s in range(n_stages):
            pstage = tmap(lambda a: a[s], packed_params)  # noqa: B023
            if rng_key is None:
                h, bufs[s] = stage_fns[s](pstage, bufs[s], h)
            else:
                h, bufs[s] = stage_fns[s](pstage, bufs[s], h,
                                          stage_rng_key(rng_key, s, m))
        outs.append({k: h[k][:out_sizes[k]] for k in out_sizes})
    out = {k: jnp.stack([o[k] for o in outs]) for k in out_sizes}
    new_bufs = tmap(lambda *rows: jnp.stack(rows), *bufs)
    return out, new_bufs
