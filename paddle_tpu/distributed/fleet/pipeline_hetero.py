"""Heterogeneous SPMD pipeline engine: arbitrary per-stage graphs + buffers.

Counterpart of the reference's general pipeline — `SegmentLayers`
(`python/paddle/distributed/fleet/meta_parallel/parallel_layers/pp_layers.py:93`)
segments ANY layer list (uniform / param-count / manual) and each stage runs its
own sub-graph (`pp_layers.py:209`), including BN layers with running stats.
The homogeneous engine (`fleet/pipeline.py`) requires structurally identical,
buffer-free stages; this module removes both restrictions, TPU-style:

- Each stage's parameter tree is FLATTENED into one f32 vector, padded to the
  widest stage, and stacked into a [pp, max_len] array sharded over 'pp' — so
  every rank holds exactly one stage's weights (1/pp of the model) even when
  stages differ structurally. Buffers (BN running stats) get the same packing
  and ride the schedule as per-rank state, updated only on valid ticks.
- Activations crossing stage boundaries are packed into fixed-size f32
  buffers (padded to the widest boundary), so `lax.ppermute` can hand them to
  the next stage even when boundary shapes differ (a ResNet's stage cut
  changes [B,C,H,W] between stages; the reference's p2p layer solves this
  with a tensor-meta handshake, `pp_utils/p2p_communication.py:74-154`).
- Inside the shard_map body, `lax.switch(axis_index('pp'), branches)` selects
  the rank's stage sub-graph; XLA compiles all branches into one SPMD program.
  The backward pipeline (reversed ring + branch transposes) falls out of vjp.

Packing is exact for f32/bf16/f16 (sub-ranges of f32) and for integers up to
2^24 (float32 mantissa); pipeline-boundary ints above that are rejected.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.fleet.pipeline import (
    functional_rng, stage_rng_key, template_rng_guard)


# Packing carrier dtype. float32 default; tests (and x64 users chasing exact
# parity) may set float64 — ResNet50-depth f32 reassociation noise is ~1e-3
# on logits, while the f64 carrier agrees with the serial run to 1e-7.
CARRIER_DTYPE = jnp.float32


def _nelems(shape):
    return int(np.prod(shape)) if len(shape) else 1


def leaf_metas(arrays):
    return [(tuple(a.shape), jnp.result_type(a.dtype)) for a in arrays]


def packed_len(metas):
    return sum(_nelems(s) for s, _ in metas)


def _check_packable(metas, what, concrete=None):
    """Reject dtypes the f32 carrier cannot round-trip. 64-bit ints are
    rejected statically; for CONCRETE arrays (params/buffers, packed
    eagerly) int32 VALUES beyond the f32 mantissa (2^24) are rejected too.
    Traced boundary activations cannot be value-checked — ints there (e.g.
    token ids) must stay under 2^24, see the module docstring."""
    for i, (shape, dt) in enumerate(metas):
        if not jnp.issubdtype(dt, jnp.integer):
            continue
        if jnp.dtype(dt).itemsize > 4:
            raise NotImplementedError(
                f"heterogeneous pipeline cannot pack {what} of dtype {dt} "
                "(f32 carrier); cast to int32/float at the stage boundary")
        if concrete is not None:
            a = concrete[i]
            if a.size and int(np.abs(np.asarray(a)).max()) > (1 << 24):
                raise NotImplementedError(
                    f"heterogeneous pipeline cannot pack {what}: {dt} "
                    "values exceed 2^24 and would be rounded by the f32 "
                    "carrier")


def pack_leaves(arrays, length):
    """Flatten+concat arrays as the carrier dtype, zero-padded to
    ``length``."""
    parts = [jnp.ravel(a).astype(CARRIER_DTYPE) for a in arrays]
    flat = (jnp.concatenate(parts) if parts
            else jnp.zeros((0,), CARRIER_DTYPE))
    pad = length - flat.shape[0]
    return jnp.pad(flat, (0, pad)) if pad else flat


def unpack_leaves(flat, metas):
    out, off = [], 0
    for shape, dtype in metas:
        n = _nelems(shape)
        out.append(flat[off:off + n].reshape(shape).astype(dtype))
        off += n
    return out


def spmd_pipeline_hetero(stage_fns, n_stages, n_micro, packed_params,
                         packed_bufs, xm_flat, out_len, mesh, rng_key=None):
    """GPipe schedule over heterogeneous stages.

    stage_fns: per-stage ``fn(param_flat, buf_flat, x_flat[, key]) ->
    (y_flat, new_buf_flat)`` where y_flat is padded to the shared activation
    length; branches must agree on output shapes (they do, by padding).
    packed_params: [n_stages, plen] f32 (row s = stage s params).
    packed_bufs:   [n_stages, blen] f32 (row s = stage s buffers).
    xm_flat: [n_micro, act_len] f32 — stage-0 inputs, one row per microbatch.
    out_len: valid prefix of the final stage's output rows.
    Returns (outs [n_micro, out_len] replicated, new_bufs [n_stages, blen]).
    """
    act_len = xm_flat.shape[1]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def per_rank(params, bufs, xs, *key_data):
        p = params[0]                      # [1, plen] local block -> [plen]
        buf = bufs[0]
        r = jax.lax.axis_index("pp")
        is_first = (r == 0)
        is_last = (r == n_stages - 1)
        base_key = (jax.random.wrap_key_data(key_data[0])
                    if key_data else None)
        carry = jnp.zeros((act_len,), CARRIER_DTYPE)
        ys_hist = []
        total_ticks = n_micro + n_stages - 1
        for t in range(total_ticks):
            feed = xs[min(t, n_micro - 1)]
            x0 = jnp.where(is_first, feed, carry) if t < n_micro else carry
            m_id = jnp.clip(t - r, 0, n_micro - 1)
            if base_key is not None:
                key = stage_rng_key(base_key, r, m_id)
                branches = [
                    (lambda pp_, bb_, xx_, kk_, _f=f: _f(pp_, bb_, xx_, kk_))
                    for f in stage_fns]
                y, buf_new = jax.lax.switch(r, branches, p, buf, x0, key)
            else:
                branches = [
                    (lambda pp_, bb_, xx_, _f=f: _f(pp_, bb_, xx_))
                    for f in stage_fns]
                y, buf_new = jax.lax.switch(r, branches, p, buf, x0)
            # buffer updates (BN running stats) only land on ticks where this
            # rank held a real microbatch — warmup/drain garbage is masked
            valid = (t - r >= 0) & (t - r < n_micro)
            buf = jnp.where(valid, buf_new, buf)
            # stash per-tick outputs; stacking at the end avoids the
            # per-tick in-place buffer versions that defeated XLA's
            # aliasing in the homogeneous engine (see fleet/pipeline.py)
            ys_hist.append(y)
            if t < total_ticks - 1:
                carry = jax.lax.ppermute(y, "pp", perm)
        outs = jnp.stack([ys_hist[m + n_stages - 1][:out_len]
                          for m in range(n_micro)])
        outs = jax.lax.psum(
            jnp.where(is_last, outs, jnp.zeros_like(outs)), "pp")
        return outs, buf[None]

    extra, extra_specs = (), ()
    if rng_key is not None:
        extra = (jax.random.key_data(rng_key),)
        extra_specs = (P(),)
    f = jax.shard_map(
        per_rank, mesh=mesh,
        in_specs=(P("pp", None), P("pp", None), P()) + extra_specs,
        out_specs=(P(), P("pp", None)),
        axis_names={"pp"},
        # see fleet/pipeline.py: stage bodies may run with_sharding_constraint
        # on AUTO axes, which the vma checker rejects inside manual regions
        check_vma=False)
    return f(packed_params, packed_bufs, xm_flat, *extra)


def hetero_serial_reference(stage_fns, n_stages, n_micro, packed_params,
                            packed_bufs, xm_flat, out_len, rng_key=None):
    """Single-device oracle: same microbatching, same packing, same
    `stage_rng_key` derivation, same per-stage buffer update order —
    the parity reference for tests (cf. pipeline_serial_reference)."""
    bufs = [packed_bufs[s] for s in range(n_stages)]
    outs = []
    for m in range(n_micro):
        h = xm_flat[m]
        for s in range(n_stages):
            if rng_key is None:
                h, bufs[s] = stage_fns[s](packed_params[s], bufs[s], h)
            else:
                h, bufs[s] = stage_fns[s](packed_params[s], bufs[s], h,
                                          stage_rng_key(rng_key, s, m))
        outs.append(h[:out_len])
    return jnp.stack(outs), jnp.stack(bufs)
