"""Fleet facade (ref: `python/paddle/distributed/fleet/fleet.py` — init :168,
distributed_model, distributed_optimizer :1032)."""
from __future__ import annotations

from paddle_tpu.distributed.fleet.base import (
    DistributedStrategy, CommunicateTopology, HybridCommunicateGroup,
    PaddleCloudRoleMaker,
)


class Fleet:
    def __init__(self):
        self._role_maker = None
        self._strategy = None
        self._hcg = None
        self._is_initialized = False

    def init(self, role_maker=None, is_collective=True, strategy=None, log_level=2):
        from paddle_tpu.distributed.parallel import init_parallel_env
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=is_collective)
        self._strategy = strategy or DistributedStrategy()
        init_parallel_env()
        hc = self._strategy.hybrid_configs
        topo = CommunicateTopology(
            hybrid_group_names=["data", "pipe", "sharding", "model"],
            dims=[hc.get("dp_degree", 1), hc.get("pp_degree", 1),
                  hc.get("sharding_degree", 1), hc.get("mp_degree", 1)])
        self._hcg = HybridCommunicateGroup(topo)
        self._is_initialized = True
        return self

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def worker_index(self):
        return self._role_maker._worker_index

    @property
    def worker_num(self):
        return self._role_maker._worker_num

    def is_first_worker(self):
        return self._role_maker._is_first_worker()

    def barrier_worker(self):
        from paddle_tpu.distributed.parallel import barrier
        barrier()

    def distributed_model(self, model):
        """Wrap per the active parallel mode (ref fleet.distributed_model)."""
        from paddle_tpu.distributed.fleet import meta_parallel as mpu
        mode = self._hcg.get_parallel_mode()
        if mode == "pipeline":
            return mpu.PipelineParallel(model, self._hcg, self._strategy)
        if mode == "tensor":
            return mpu.TensorParallel(model, self._hcg, self._strategy)
        from paddle_tpu.distributed.parallel_wrappers import DataParallel
        return DataParallel(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        from paddle_tpu.distributed.fleet.meta_optimizers import (
            apply_meta_optimizers)
        from paddle_tpu.distributed.fleet.meta_parallel import (
            HybridParallelOptimizer)
        strategy = strategy or self._strategy
        optimizer = apply_meta_optimizers(optimizer, strategy, self._hcg)
        return HybridParallelOptimizer(optimizer, self._hcg, strategy)

    def distributed_scaler(self, scaler):
        return scaler


_fleet_singleton = Fleet()


def init(role_maker=None, is_collective=True, strategy=None, log_level=2):
    return _fleet_singleton.init(role_maker, is_collective, strategy, log_level)


def distributed_model(model):
    return _fleet_singleton.distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return _fleet_singleton.distributed_optimizer(optimizer, strategy)


def get_hybrid_communicate_group():
    return _fleet_singleton.get_hybrid_communicate_group()


def worker_index():
    from paddle_tpu.distributed.parallel import get_rank
    return get_rank()


def worker_num():
    from paddle_tpu.distributed.parallel import get_world_size
    return get_world_size()


def is_first_worker():
    return worker_index() == 0


def barrier_worker():
    from paddle_tpu.distributed.parallel import barrier
    barrier()
