"""Fleet base objects: DistributedStrategy, topology, role makers.

Ref: `framework/distributed_strategy.proto` (29 messages) /
`fleet/base/distributed_strategy.py:111`; `fleet/base/topology.py:53,139`.
The 4-D dp×mp×pp×sharding topology maps onto mesh axes (see distributed.mesh).
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.distributed.mesh import auto_mesh, get_mesh, set_mesh
from paddle_tpu.distributed.collective import new_group


class DistributedStrategy:
    """Attribute-bag mirroring the reference's strategy proto fields used by the
    collective path (PS-only fields are accepted but inert)."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
        }
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.sharding_configs = {"stage": 1, "offload": False}
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 32768.0, "use_pure_fp16": False,
                            "custom_white_list": [], "custom_black_list": []}
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "momentum": 0.9,
                            "sparsity": 0.999}
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1, "begin_step": 1}
        self.fp16_allreduce = False
        self.sharding = False
        self.pipeline = False
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.sync_nccl_allreduce = True
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.heter_ccl_mode = False
        self.is_fl_ps_mode = False
        self.a_sync = False
        self.a_sync_configs = {}
        self.auto = False
        self.semi_auto = False
        self.without_graph_optimization = True

    def to_dict(self):
        return dict(self.__dict__)

    def __repr__(self):
        return f"DistributedStrategy({self.hybrid_configs})"


class CommunicateTopology:
    """ref: `fleet/base/topology.py:53` — named N-D rank grid."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = None
        self._world = int(np.prod(self._dims))
        arr = np.arange(self._world).reshape(self._dims)
        self._rank_grid = arr

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return int(self._rank_grid[coord])

    def get_coord(self, rank):
        coord = np.unravel_index(rank, self._dims)
        return tuple(int(c) for c in coord)

    def get_axis_list(self, axis_name, index):
        ax = self._parallel_names.index(axis_name)
        sl = [slice(None)] * len(self._dims)
        sl[ax] = index
        return sorted(int(r) for r in self._rank_grid[tuple(sl)].reshape(-1))

    def get_comm_list(self, axis_name):
        """All rank-groups that communicate along axis_name."""
        ax = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._rank_grid, ax, -1)
        return [sorted(int(r) for r in row)
                for row in moved.reshape(-1, self._dims[ax])]


class HybridCommunicateGroup:
    """ref: `fleet/base/topology.py:139` — creates per-strategy comm groups
    (:346-402). Here each group is a named mesh axis; the jax Mesh is installed
    globally so layers/sharding pick it up."""

    AXIS_MAP = {"data": "dp", "pipe": "pp", "sharding": "sdp", "model": "mp",
                "sep": "sp"}

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        from paddle_tpu.distributed.parallel import get_rank
        self.global_rank = get_rank()
        names = topology.get_hybrid_group_names()
        self._dp_degree = topology.get_dim("data") if "data" in names else 1
        self._pp_degree = topology.get_dim("pipe") if "pipe" in names else 1
        self._sharding_degree = topology.get_dim("sharding") \
            if "sharding" in names else 1
        self._mp_degree = topology.get_dim("model") if "model" in names else 1
        self._sep_degree = topology.get_dim("sep") if "sep" in names else 1

        import jax
        n_dev = len(jax.devices())
        need = (self._dp_degree * self._pp_degree * self._sharding_degree *
                self._mp_degree * self._sep_degree)
        if need == n_dev:
            auto_mesh(dp=self._dp_degree, mp=self._mp_degree, pp=self._pp_degree,
                      sp=self._sep_degree, sdp=self._sharding_degree)

        coord = self._topo.get_coord(self.global_rank) \
            if self.global_rank < self._topo.world_size() else \
            (0,) * len(self._topo._dims)
        self._coord = dict(zip(self._topo.get_hybrid_group_names(), coord))

        self._dp_group = new_group(
            self._topo.get_axis_list("data", 0) if "data" in names else [0],
            axis_name="dp")
        self._mp_group = new_group(
            self._topo.get_axis_list("model", 0) if "model" in names else [0],
            axis_name="mp")
        self._pp_group = new_group(
            self._topo.get_axis_list("pipe", 0) if "pipe" in names else [0],
            axis_name="pp")
        self._sharding_group = new_group(
            self._topo.get_axis_list("sharding", 0) if "sharding" in names
            else [0], axis_name="sdp")

    @property
    def topology(self):
        return self._topo

    def get_parallel_mode(self):
        if self._pp_degree > 1:
            return "pipeline"
        if self._mp_degree > 1:
            return "tensor"
        if self._sharding_degree > 1:
            return "sharding"
        return "data"

    # degrees
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    # ranks within each axis
    def get_data_parallel_rank(self):
        return self._coord.get("data", 0)

    def get_model_parallel_rank(self):
        return self._coord.get("model", 0)

    def get_stage_id(self):
        return self._coord.get("pipe", 0)

    def get_sharding_parallel_rank(self):
        return self._coord.get("sharding", 0)

    # groups
    def get_data_parallel_group(self):
        return self._dp_group

    def get_model_parallel_group(self):
        return self._mp_group

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_check_parallel_group(self, *a, **k):
        return self._mp_group

    def get_rank_from_stage(self, stage_id, **kwargs):
        coord = dict(self._coord)
        coord["pipe"] = stage_id
        return self._topo.get_rank(**coord)

    def get_p2p_groups(self):
        return None

    def get_model_parallel_group_src_rank(self):
        return self._mp_group.ranks[0]

    def get_data_parallel_group_src_rank(self):
        return self._dp_group.ranks[0]


class PaddleCloudRoleMaker:
    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective

    def _worker_num(self):
        from paddle_tpu.distributed.parallel import get_world_size
        return get_world_size()

    def _worker_index(self):
        from paddle_tpu.distributed.parallel import get_rank
        return get_rank()

    def _is_first_worker(self):
        return self._worker_index() == 0


UserDefinedRoleMaker = PaddleCloudRoleMaker
