"""Hybrid-parallel layers & runtimes.

Ref: Megatron-style TP layers `fleet/layers/mpu/mp_layers.py`
(VocabParallelEmbedding:38, ColumnParallelLinear:176, RowParallelLinear:335,
ParallelCrossEntropy:501), TP RNG `layers/mpu/random.py:34`, pipeline
`meta_parallel/parallel_layers/pp_layers.py:209` + runtime
`meta_parallel/pipeline_parallel.py:33` (1F1B at :119).

TPU-native: TP layers hold the FULL logical weight with a NamedSharding over the
'mp' mesh axis — GSPMD inserts the identity/allreduce pair the reference codes as
`_c_identity`/`_mp_allreduce` (`mp_ops.py:33,235`). The pipeline runtime does
micro-batch accumulation (GPipe-equivalent loss semantics, loss-parity oracle as in
`hybrid_parallel_pp_*` tests); stage placement over the 'pp' axis is annotation.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from paddle_tpu.core.tensor import Tensor, Parameter
from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.framework.param_attr import ParamAttr
from paddle_tpu.distributed.mesh import get_mesh
from paddle_tpu.ops.common import ensure_tensor


def _mesh_axis_size(axis):
    mesh = get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return mesh.shape[axis]


def _place_param(p: Parameter, spec: PartitionSpec):
    mesh = get_mesh()
    if mesh is None:
        return
    if not isinstance(p._data, jax.core.Tracer):
        p._write(jax.device_put(p._data, NamedSharding(mesh, spec)))


# True while a heterogeneous pipeline stage body traces: sharding
# constraints on auto axes inside the lax.switch branches segfault jax's
# linearizer (pjit-in-switch-in-manual-shard_map), and the packed per-stage
# params carry no 'mp' sub-sharding for them to pin anyway — so mpu layers
# run unconstrained there and GSPMD picks layouts freely.
_IN_HETERO_STAGE = False


def _constrain(t: Tensor, spec: PartitionSpec) -> Tensor:
    mesh = get_mesh()
    if mesh is None or _IN_HETERO_STAGE or not isinstance(
            t._data, jax.core.Tracer):
        return t
    from paddle_tpu.core.autograd import apply
    sh = NamedSharding(mesh, spec)
    return apply(lambda a: jax.lax.with_sharding_constraint(a, sh), t,
                 op_name="sharding_constraint")


_U = PartitionSpec.UNCONSTRAINED


def _last_dim_spec(nd, last):
    """Constrain ONLY the feature (last) dim; batch/seq dims stay
    UNCONSTRAINED so GSPMD keeps whatever dp/sp sharding flows in. Pinning
    them (P() replication) made the partitioner flip between dp x sp and mp
    layouts in the linear backward — the 'Involuntary full rematerialization'
    the round-2 review flagged."""
    return PartitionSpec(*([_U] * (nd - 1)), last)


# --------------------------------------------------------------------- TP RNG


class RNGStatesTracker:
    """ref: `fleet/layers/mpu/random.py:34` — named RNG states so dropout inside
    TP regions is per-rank while data-parallel regions stay replicated."""

    def __init__(self):
        self.states_ = {}

    def add(self, name, seed):
        from paddle_tpu.ops.random import Generator
        if name in self.states_:
            raise ValueError(f"rng state {name} already exists")
        self.states_[name] = Generator(seed)

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = states

    @contextlib.contextmanager
    def rng_state(self, name="model_parallel_rng"):
        if name not in self.states_:
            self.add(name, np.random.randint(1, 2**31 - 1))
        from paddle_tpu.ops import random as rnd
        prev = rnd._default_generator
        rnd._default_generator = self.states_[name]
        try:
            yield
        finally:
            rnd._default_generator = prev


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    import paddle_tpu
    base = seed or np.random.randint(1, 2**20)
    paddle_tpu.seed(base)
    tracker = get_rng_state_tracker()
    tracker.states_.clear()
    tracker.add("model_parallel_rng", base + 1024)


# --------------------------------------------------------------------- TP layers


class VocabParallelEmbedding(Layer):
    """ref `mp_layers.py:38`: embedding table sharded over vocab; out-of-shard
    lookups masked then allreduced — GSPMD derives that from the sharding."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num = num_embeddings
        attr = ParamAttr._to_attr(weight_attr)
        if attr is None:
            attr = ParamAttr(initializer=I.XavierNormal())
        elif isinstance(attr, ParamAttr) and attr.initializer is None:
            attr.initializer = I.XavierNormal()
        self.weight = self.create_parameter((num_embeddings, embedding_dim),
                                            attr=attr)
        _place_param(self.weight, PartitionSpec("mp", None))

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    """ref `mp_layers.py:176`: W [in, out] sharded on out; gather_output
    controls whether the result is gathered back (replicated)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=ParamAttr._to_attr(weight_attr))
        _place_param(self.weight, PartitionSpec(None, "mp"))
        if has_bias is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            _place_param(self.bias, PartitionSpec("mp"))

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return _constrain(out, _last_dim_spec(out.ndim, None))
        return _constrain(out, _last_dim_spec(out.ndim, "mp"))


class RowParallelLinear(Layer):
    """ref `mp_layers.py:335`: W [in, out] sharded on in; partial results are
    psum'd (GSPMD emits the allreduce)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=ParamAttr._to_attr(weight_attr))
        _place_param(self.weight, PartitionSpec("mp", None))
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        x = ensure_tensor(x)
        if self.input_is_parallel:
            x = _constrain(x, _last_dim_spec(x.ndim, "mp"))
        out = F.linear(x, self.weight, self.bias)
        return _constrain(out, _last_dim_spec(out.ndim, None))


class ParallelCrossEntropy(Layer):
    """ref `mp_layers.py:501` (`c_softmax_with_cross_entropy`): with logits
    sharded over classes GSPMD computes the softmax reduction across 'mp'."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


class TensorParallel(Layer):
    """Dygraph wrapper (ref `meta_parallel/tensor_parallel.py:27`): in the
    reference it broadcasts params inside mp group at init; sharded params here
    are already consistent by construction."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)


# --------------------------------------------------------------------- pipeline


class LayerDesc:
    """ref `pp_layers.py` LayerDesc — lazy layer construction per stage."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """ref `pp_layers.py` SharedLayerDesc — layers shared across stages (e.g.
    embedding/output head weight tying)."""

    def __init__(self, key, layer_cls, *args, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """ref `pp_layers.py:93` — uniform / param-count / layer-class / manual
    segmentation. ``method`` may also be an explicit bounds list
    ``[0, ..., n]`` of length num_parts+1 (the reference's manual mode)."""

    def __init__(self, layers, num_parts, method="uniform"):
        self.layers = layers
        self.num_parts = num_parts
        self.method = method

    def _param_counts(self):
        counts = []
        for l in self.layers:
            if isinstance(l, LayerDesc):
                counts.append(1)        # lazy descs: fall back to uniform weight
            else:
                ps = getattr(l, "parameters", None)
                counts.append(sum(int(np.prod(p.shape)) for p in ps())
                              if callable(ps) else 0)
        return counts

    def do_segment(self):
        n = len(self.layers)
        if isinstance(self.method, (list, tuple)):
            bounds = [int(b) for b in self.method]
            if (len(bounds) != self.num_parts + 1 or bounds[0] != 0
                    or bounds[-1] != n
                    or any(a > b for a, b in zip(bounds, bounds[1:]))):
                raise ValueError(
                    f"manual segment bounds {bounds} must be monotonic, "
                    f"length {self.num_parts + 1}, spanning [0, {n}]")
            return bounds
        if self.method == "param":
            # greedy: cut where cumulative param count crosses each 1/P mark
            counts = self._param_counts()
            total = max(sum(counts), 1)
            bounds = [0]
            acc = 0
            for i, c in enumerate(counts):
                acc += c
                if (len(bounds) < self.num_parts
                        and acc >= total * len(bounds) / self.num_parts):
                    bounds.append(i + 1)
            while len(bounds) <= self.num_parts:
                bounds.append(n)
            return bounds[: self.num_parts + 1]
        if self.method == "uniform":
            base = n // self.num_parts
            extra = n % self.num_parts
            bounds = [0]
            for i in range(self.num_parts):
                bounds.append(bounds[-1] + base + (1 if i < extra else 0))
            return bounds
        if self.method.startswith("layer:"):
            cls_name = self.method.split(":", 1)[1]
            flags = [1 if type(l).__name__ == cls_name or (
                isinstance(l, LayerDesc) and l.layer_cls.__name__ == cls_name)
                else 0 for l in self.layers]
            total = sum(flags)
            per = total // self.num_parts
            bounds = [0]
            count = 0
            for i, f in enumerate(flags):
                count += f
                if len(bounds) < self.num_parts and count >= per * len(bounds):
                    bounds.append(i + 1)
            while len(bounds) <= self.num_parts:
                bounds.append(n)
            return bounds[: self.num_parts + 1]
        raise ValueError(f"unknown segment method {self.method}")


class PipelineLayer(Layer):
    """ref `pp_layers.py:209`. Holds the full layer list; segments map to pp
    stages.

    When the current mesh has a 'pp' axis of size > 1 and the layer list
    contains a homogeneous run covering the stage segments (e.g. N identical
    transformer blocks), that run executes on the SPMD pipeline engine
    (`fleet/pipeline.py`): per-stage weights live stacked on a leading [pp]
    axis sharded over 'pp', and micro-batches circulate between stages via
    lax.ppermute inside shard_map — a real pipeline with p2p, not grad
    accumulation. Heterogeneous prefix/suffix layers (embedding, final norm,
    head) run outside the pipelined region. Without a pp axis, falls back to
    sequential execution (the reference's single-stage behavior)."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, num_virtual_pipeline_stages=None,
                 micro_batches=None, **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        self._num_stages = num_stages or (
            topology.get_dim("pipe") if topology else 1)
        self._shared = {}
        built = []
        for desc in layers:
            if isinstance(desc, SharedLayerDesc):
                if desc.key in self._shared:
                    layer = self._shared[desc.key]
                else:
                    layer = desc.build_layer()
                    self._shared[desc.key] = layer
                built.append((layer, desc.forward_func))
            elif isinstance(desc, LayerDesc):
                built.append((desc.build_layer(), None))
            else:
                built.append((desc, None))
        self.run_funcs = built
        self._segments = SegmentLayers(
            [l for l, _ in built], self._num_stages, seg_method).do_segment()
        self._recompute_interval = recompute_interval
        self._pp_micro = micro_batches
        self._pp_chunks = int(num_virtual_pipeline_stages or 1)
        self._pp_mode = False
        self._pp_hetero = False
        if self._num_stages > 1 and _mesh_axis_size("pp") == self._num_stages:
            # explicit balanced/manual segmentation = the user wants THOSE
            # stage cuts — the heterogeneous engine honors them; "uniform"
            # keeps the homogeneous fast path (stacked params + interleave,
            # preserves mp sub-shardings) when the layer list allows it
            prefer_hetero = (seg_method == "param"
                             or isinstance(seg_method, (list, tuple)))
            hetero_err = None
            if prefer_hetero:
                try:
                    self._init_hetero_pipeline(built)
                except NotImplementedError as e:
                    hetero_err = e
                if not self._pp_mode:
                    self._init_spmd_pipeline(built)
            else:
                self._init_spmd_pipeline(built)
                if not self._pp_mode:
                    try:
                        self._init_hetero_pipeline(built)
                    except NotImplementedError as e:
                        hetero_err = e
            if not self._pp_mode:
                import warnings
                warnings.warn(
                    f"PipelineLayer: pp={self._num_stages} was requested "
                    f"but SPMD pipelining is unavailable ({hetero_err}); "
                    "FALLING BACK TO SEQUENTIAL execution — no pipeline "
                    "parallelism will happen", stacklevel=3)
        elif self._num_stages > 1:
            import warnings
            warnings.warn(
                f"PipelineLayer: num_stages={self._num_stages} but the "
                f"current mesh has no matching 'pp' axis "
                f"(size {_mesh_axis_size('pp')}); running SEQUENTIALLY",
                stacklevel=3)
        if not self._pp_mode:
            from paddle_tpu.nn.layers.container import LayerList
            self._layers_list = LayerList([l for l, _ in built])

    # ---------------------------------------------------------- SPMD pp setup

    @staticmethod
    def _layer_sig(layer):
        """Homogeneity signature: class + param/buffer shapes + scalar config.
        Scalar attributes (num_heads, dropout p, eps, ...) are part of the
        signature — stages run on the stage-0 template, so layers that differ
        in anything but weight VALUES must not be treated as interchangeable."""
        def cfg(l):
            scalars = tuple(sorted(
                (k, v) for k, v in vars(l).items()
                if isinstance(v, (int, float, bool, str, type(None)))
                and not k.startswith("__")))
            subs = tuple(cfg(s) for s in getattr(
                l, "_sub_layers", {}).values())
            return (type(l).__name__, scalars, subs)

        if list(getattr(layer, "buffers", lambda: [])()):
            # buffered layers (BN running stats...) can't pipeline: only
            # parameters are stacked per stage, so every stage would read
            # stage-0's buffer values
            return None
        return (cfg(layer),
                tuple((tuple(p.shape), str(p.dtype))
                      for p in layer.parameters()))

    def _init_spmd_pipeline(self, built):
        from paddle_tpu.nn.layers.container import LayerList
        from paddle_tpu.distributed.fleet.pipeline import stack_stage_params
        n = len(built)
        sigs = [self._layer_sig(l) if f is None else None for l, f in built]
        # longest run of identical signatures
        best = (0, 0)
        i = 0
        while i < n:
            j = i
            while j < n and sigs[j] is not None and sigs[j] == sigs[i]:
                j += 1
            if j - i > best[1] - best[0]:
                best = (i, j)
            i = max(j, i + 1)
        start, end = best
        run_len = end - start
        s_total = self._num_stages * self._pp_chunks
        per = run_len // s_total
        if per == 0:
            if self._pp_chunks > 1:                 # too few layers to chunk
                self._pp_chunks = 1
                s_total = self._num_stages
                per = run_len // s_total
            if per == 0:
                return                              # fall back to sequential
        # trim a non-divisible remainder into the sequential prefix
        start = start + (run_len - per * s_total)
        end = start + per * s_total
        mesh = get_mesh()
        # rank-major stacking: rank r's local block holds its V chunks in
        # order, chunk c of rank r = LOGICAL stage c*S + r (see
        # spmd_pipeline_interleaved's layout contract)
        trees = []
        for r in range(self._num_stages):
            for c in range(self._pp_chunks):
                ls = c * self._num_stages + r
                seg = built[start + ls * per: start + (ls + 1) * per]
                trees.append([p._data for l, _ in seg
                              for p in l.parameters()])
        stacked = stack_stage_params(trees, mesh)
        self._pp_run = (start, end)
        self._pp_per_stage = per
        # template = stage-0 segment; its params get rebound per stage inside
        # the pipeline body. Template layers / pipelined originals stay
        # UNREGISTERED (the stacked params replace them).
        self._pp_template = [built[start + i] for i in range(per)]
        self._pp_template_params = [
            p for l, _ in self._pp_template for p in l.parameters()]
        self._pp_stacked = []
        for i, arr in enumerate(stacked):
            prm = Parameter(arr)
            prm.name = f"pp_stage_param_{i}"
            self.add_parameter(f"pp_stage_param_{i}", prm)
            self._pp_stacked.append(prm)
        prefix = [l for l, _ in built[:start]]
        suffix = [l for l, _ in built[end:]]
        self._layers_list = LayerList(prefix + suffix)
        self._pp_mode = True

    def get_stage_layers(self, stage_id):
        lo, hi = self._segments[stage_id], self._segments[stage_id + 1]
        return self.run_funcs[lo:hi]

    # -------------------------------------------------------- hetero pp setup

    def _init_hetero_pipeline(self, built):
        """Heterogeneous/buffered stages (ref `pp_layers.py:93,209`): the
        segment bounds from ``seg_method`` become the stages; each stage's
        params/buffers are packed into per-stage f32 vectors stacked on a
        'pp'-sharded leading axis (see fleet/pipeline_hetero.py). Unlike the
        homogeneous engine, stages may differ structurally and may carry
        buffers (BN running stats)."""
        from paddle_tpu.nn.layers.container import LayerList
        from paddle_tpu.distributed.fleet import pipeline_hetero as ph
        mesh = get_mesh()
        n_stages = self._num_stages
        if self._pp_chunks > 1:
            raise NotImplementedError(
                "interleaved virtual stages require homogeneous layers")
        segs = self._segments
        stage_slices = [built[segs[s]:segs[s + 1]] for s in range(n_stages)]
        if any(len(sl) == 0 for sl in stage_slices):
            raise NotImplementedError(
                f"segment bounds {segs} produce an empty pipeline stage")
        # cross-stage tying is supported for PARAMETERS (grad hook below);
        # a shared layer carrying BUFFERS (BN running stats) would update
        # each stage row independently with no reconciliation — stats would
        # silently diverge from serial, so it stays a loud error
        seen_stage = {}
        for s, sl in enumerate(stage_slices):
            for layer, _ in sl:
                first = seen_stage.setdefault(id(layer), s)
                if first != s and list(layer.buffers()):
                    raise NotImplementedError(
                        "a SharedLayerDesc layer with BUFFERS (e.g. BN "
                        "running stats) appears in two pipeline stages — "
                        "cross-stage tying reconciles parameter grads, but "
                        "per-stage buffer updates have no single owner")
        param_objs, buf_objs, pmetas, bmetas = [], [], [], []
        for sl in stage_slices:
            ps, bs, seen = [], [], set()
            for layer, _ in sl:
                for p in layer.parameters():
                    if id(p) not in seen:
                        seen.add(id(p))
                        ps.append(p)
                for b in layer.buffers():
                    if id(b) not in seen:
                        seen.add(id(b))
                        bs.append(b)
            param_objs.append(ps)
            buf_objs.append(bs)
            pmetas.append(ph.leaf_metas([p._data for p in ps]))
            bmetas.append(ph.leaf_metas([b._data for b in bs]))
        plens = ph.merge_lengths([ph.bucket_sizes(m) for m in pmetas])
        blens = ph.merge_lengths([ph.bucket_sizes(m) for m in bmetas])
        packed_p = {k: [] for k in plens}
        packed_b = {k: [] for k in blens}
        for s in range(n_stages):
            row_p = ph.pack_buckets([p._data for p in param_objs[s]],
                                    pmetas[s], plens)
            row_b = ph.pack_buckets([b._data for b in buf_objs[s]],
                                    bmetas[s], blens)
            for k in plens:
                packed_p[k].append(row_p[k])
            for k in blens:
                packed_b[k].append(row_b[k])
        spec = NamedSharding(mesh, PartitionSpec("pp", None))
        # a SharedLayerDesc layer in two stages = cross-stage weight tying
        # (ref `pp_layers.py:381-431` shared-comm groups): its param leaves
        # occupy slots in BOTH stage rows. The copies start equal (packed
        # from one object); a grad hook sums the slot grads and broadcasts
        # the total to every copy — with identical values, grads, and
        # (flat, zero-init) optimizer state, the copies stay bit-synced
        # without any extra communication, the SPMD analog of the
        # reference's allreduce over the shared-weight comm group.
        locs = {}
        for s, ps in enumerate(param_objs):
            for li, p in enumerate(ps):
                locs.setdefault(id(p), []).append((s, li))
        tie_groups = {}                      # bucket key -> [ [(s,off,n)..] ]
        p_layouts = [ph.bucket_layout(m) for m in pmetas]
        for pid, where in locs.items():
            if len(where) < 2:
                continue
            slots = []
            for s, li in where:
                k, off = p_layouts[s][li]
                n = ph._nelems(pmetas[s][li][0])
                slots.append((s, off, n))
            tie_groups.setdefault(k, []).append(slots)
        self._ph_params, self._ph_bufs = {}, {}
        for k in sorted(plens):
            prm = Parameter(jax.device_put(jnp.stack(packed_p[k]), spec))
            prm.name = f"pp_hetero_params_{k}"
            self.add_parameter(f"pp_hetero_params_{k}", prm)
            if k in tie_groups:
                prm.register_hook(self._make_tie_hook(tie_groups[k]))
                # every slot after a group's first is a grad DUPLICATE:
                # global-norm clip must not re-count it (nn/clip.py)
                prm._tied_dup_slots = [slot for slots in tie_groups[k]
                                       for slot in slots[1:]]
            self._ph_params[k] = prm
        for k in sorted(blens):
            buf = Tensor(jax.device_put(jnp.stack(packed_b[k]), spec),
                         _internal=True)
            buf.stop_gradient = True
            self.register_buffer(f"pp_hetero_bufs_{k}", buf)
            self._ph_bufs[k] = buf
        self._ph_param_keys = sorted(plens)
        self._ph_buf_keys = sorted(blens)
        self._ph_tie_groups = tie_groups
        self._ph_stage_slices = stage_slices
        self._ph_param_objs = param_objs
        self._ph_buf_objs = buf_objs
        self._ph_pmetas, self._ph_bmetas = pmetas, bmetas
        self._ph_plens, self._ph_blens = plens, blens
        # stage layers stay UNREGISTERED: the packed param/buffer replace them
        self._layers_list = LayerList([])
        self._pp_hetero = True
        self._pp_mode = True

    @staticmethod
    def _make_tie_hook(groups):
        def hook(g):
            arr = g._data
            for slots in groups:
                tot = None
                for s, off, n in slots:
                    piece = arr[s, off:off + n]
                    tot = piece if tot is None else tot + piece
                for s, off, n in slots:
                    arr = arr.at[s, off:off + n].set(tot)
            return Tensor(arr, stop_gradient=True, _internal=True)
        return hook

    def _hetero_stage_fn(self, s, in_meta, act_lens):
        """fn(p_buckets, b_buckets, x_buckets[, key]) ->
        (y_buckets[act_lens], b_buckets')"""
        from paddle_tpu.core import tensor as tensor_mod
        from paddle_tpu.distributed.fleet import pipeline_hetero as ph
        from paddle_tpu.distributed.fleet.pipeline import (
            functional_rng, template_rng_guard)
        players = self._ph_stage_slices[s]
        pobjs, bobjs = self._ph_param_objs[s], self._ph_buf_objs[s]
        pmetas, bmetas = self._ph_pmetas[s], self._ph_bmetas[s]
        blens = self._ph_blens

        def fn(p_buckets, b_buckets, x_buckets, key=None):
            pvals = ph.unpack_buckets(p_buckets, pmetas)
            bvals = ph.unpack_buckets(b_buckets, bmetas)
            xin = ph.unpack_buckets(x_buckets, [in_meta])[0]
            saved_p = [(t._data, t._grad_node, t._out_slot) for t in pobjs]
            saved_b = [t._data for t in bobjs]
            prev_hooks = tensor_mod.set_capture_hooks(None, None)
            for t, a in zip(pobjs, pvals):
                t._data = a
                t._grad_node = None
            for t, a in zip(bobjs, bvals):
                t._data = a
            ctx = (functional_rng(key) if key is not None else
                   template_rng_guard("the heterogeneous pipeline stage body"))
            global _IN_HETERO_STAGE
            prev_stage = _IN_HETERO_STAGE
            _IN_HETERO_STAGE = True
            try:
                with ctx:
                    out = Tensor(xin, _internal=True)
                    for layer, ffunc in players:
                        out = (ffunc(layer, out) if ffunc is not None
                               else layer(out))
                    new_bufs = [t._data for t in bobjs]  # BN wrote updates
                    y = ph.pack_buckets([out._data],
                                        ph.leaf_metas([out._data]), act_lens)
                    nb = ph.pack_buckets(new_bufs, bmetas, blens)
            finally:
                _IN_HETERO_STAGE = prev_stage
                tensor_mod.set_capture_hooks(*prev_hooks)
                for t, (d, nd, sl) in zip(pobjs, saved_p):
                    t._data = d
                    t._grad_node = nd
                    t._out_slot = sl
                for t, d in zip(bobjs, saved_b):
                    t._data = d
            return y, nb

        return fn

    def _hetero_boundary_metas(self, x, mb):
        """Abstract-eval each stage on an mb-sized input -> boundary metas.
        Hooks are disabled (original params must not enter the capture's
        read set — the packed vector replaces them) and buffer bindings are
        restored (BN's running-stat write under eval_shape is a tracer)."""
        from paddle_tpu.core import tensor as tensor_mod
        from paddle_tpu.core.autograd import no_grad
        from paddle_tpu.distributed.fleet.pipeline import functional_rng

        def raw_stage(sl):
            def f(a):
                out = Tensor(a, _internal=True)
                for layer, ffunc in sl:
                    out = ffunc(layer, out) if ffunc is not None else layer(out)
                if not isinstance(out, Tensor):
                    raise NotImplementedError(
                        "heterogeneous pipeline stages must map one tensor "
                        f"to one tensor; got {type(out).__name__}")
                return out._data
            return f

        metas = [(tuple((mb,) + tuple(x.shape[1:])),
                  jnp.result_type(x.dtype))]
        saved_b = [(t, t._data) for bl in self._ph_buf_objs for t in bl]
        prev_hooks = tensor_mod.set_capture_hooks(None, None)
        global _IN_HETERO_STAGE
        prev_stage = _IN_HETERO_STAGE
        _IN_HETERO_STAGE = True
        try:
            with no_grad(), functional_rng(jax.random.PRNGKey(0)):
                aval = jax.ShapeDtypeStruct(*metas[0])
                for sl in self._ph_stage_slices:
                    aval = jax.eval_shape(raw_stage(sl), aval)
                    metas.append((tuple(aval.shape),
                                  jnp.result_type(aval.dtype)))
        finally:
            _IN_HETERO_STAGE = prev_stage
            tensor_mod.set_capture_hooks(*prev_hooks)
            for t, d in saved_b:
                t._data = d
        return metas

    def _run_hetero_pipeline(self, x):
        from paddle_tpu.core.autograd import apply, no_grad
        from paddle_tpu.distributed.fleet import pipeline_hetero as ph
        mesh = get_mesh()
        x = ensure_tensor(x)
        n_micro = self._pp_micro or 1
        n_stages = self._num_stages
        B = int(x.shape[0])
        if B % n_micro != 0:
            raise ValueError(f"batch {B} not divisible into {n_micro} micro")
        mb = B // n_micro
        use_rng = self.training
        if use_rng and not hasattr(self, "_pp_generator"):
            from paddle_tpu.ops import random as rnd
            from paddle_tpu.ops.random import Generator
            self._pp_generator = Generator(
                rnd._default_generator.initial_seed() + 2718)
            with jax.ensure_compile_time_eval():
                self._pp_generator._state
        for sl in self._ph_stage_slices:
            for layer, _ in sl:
                layer.train() if self.training else layer.eval()
        cache_key = (tuple(mesh.axis_names), tuple(mesh.shape.items()),
                     tuple(d.id for d in mesh.devices.flat), n_micro,
                     self.training, tuple(x.shape), str(x.dtype))
        cache = getattr(self, "_ph_prim_cache", None)
        if cache is None:
            cache = self._ph_prim_cache = {}
        pkeys, bkeys = self._ph_param_keys, self._ph_buf_keys
        n_pk, n_bk = len(pkeys), len(bkeys)
        jitted = cache.get(cache_key)
        if jitted is None:
            metas = self._hetero_boundary_metas(x, mb)
            act_lens = ph.merge_lengths(
                [ph.bucket_sizes([m]) for m in metas])
            # introspection (and the bf16-boundary test): which dtypes
            # actually cross stage boundaries / sit in the packed params
            self._ph_act_lens = act_lens
            out_meta = metas[-1]
            out_sizes = ph.bucket_sizes([out_meta])
            stage_fns = [self._hetero_stage_fn(s, metas[s], act_lens)
                         for s in range(n_stages)]

            def prim(*arrays):
                packed_p = dict(zip(pkeys, arrays[:n_pk]))
                packed_b = dict(zip(bkeys, arrays[n_pk:n_pk + n_bk]))
                xa = arrays[n_pk + n_bk]
                kd = arrays[n_pk + n_bk + 1:]
                xm = xa.reshape((n_micro, mb) + xa.shape[1:])
                rows = [ph.pack_buckets([xm[m]], ph.leaf_metas([xm[m]]),
                                        act_lens) for m in range(n_micro)]
                xm_flat = {k: jnp.stack([r[k] for r in rows])
                           for k in act_lens}
                base_key = (jax.random.wrap_key_data(kd[0]) if kd else None)
                outs, new_b = ph.spmd_pipeline_hetero(
                    stage_fns, n_stages, n_micro, packed_p, packed_b,
                    xm_flat, out_sizes, mesh, rng_key=base_key)
                res = [ph.unpack_buckets(
                    {k: outs[k][m] for k in outs}, [out_meta])[0]
                       for m in range(n_micro)]
                return (jnp.concatenate(res, axis=0),
                        *[new_b[k] for k in bkeys])

            jitted = jax.jit(prim)
            cache[cache_key] = jitted
        args = ([self._ph_params[k] for k in pkeys]
                + [self._ph_bufs[k] for k in bkeys] + [x])
        if use_rng:
            kd = jax.random.key_data(self._pp_generator.next_key())
            args.append(Tensor(kd, _internal=True))
        out, *new_bs = apply(jitted, *args, op_name="spmd_pipeline_hetero")
        new_b = dict(zip(bkeys, new_bs))
        with no_grad():
            for k in bkeys:
                self._ph_bufs[k]._write(new_b[k]._data)
            # refresh the original layer buffer objects so introspection /
            # a later sequential run sees the updated running stats
            for s, (bl, bm) in enumerate(zip(self._ph_buf_objs,
                                             self._ph_bmetas)):
                if bl:
                    vals = ph.unpack_buckets(
                        {k: new_b[k]._data[s] for k in bkeys}, bm)
                    for t, v in zip(bl, vals):
                        t._data = v
        return out

    def _run_spmd_pipeline(self, x):
        from paddle_tpu.core.autograd import apply
        from paddle_tpu.distributed.fleet.pipeline import (
            spmd_pipeline_interleaved)
        mesh = get_mesh()
        tpl_params = self._pp_template_params
        tpl = self._pp_template
        n_micro = self._pp_micro or 1
        n_stages = self._num_stages
        n_chunks = self._pp_chunks
        # dropout inside stage bodies: draw ONE base key per step from a
        # DEDICATED pipeline generator (seeded off the global seed, the
        # reference's named-RNG-state pattern, `mpu/random.py:34` /
        # `model_parallel_random_seed`) and let the engine fold
        # (logical stage, microbatch) into it — nn.Dropout then works in
        # stages, deterministically in model position. A separate stream
        # keeps the GLOBAL generator untouched, so dropout-free pipelined
        # models consume exactly the same global draws as serial execution.
        use_rng = self.training
        if use_rng and not hasattr(self, "_pp_generator"):
            from paddle_tpu.ops import random as rnd
            from paddle_tpu.ops.random import Generator
            self._pp_generator = Generator(
                rnd._default_generator.initial_seed() + 2718)
            with jax.ensure_compile_time_eval():
                self._pp_generator._state  # materialize concretely, even if
                # the first pipelined call happens inside a to_static trace
        cache_key = (tuple(mesh.axis_names), tuple(mesh.shape.items()),
                     tuple(d.id for d in mesh.devices.flat),
                     n_micro, n_chunks, self.training)
        cache = getattr(self, "_pp_prim_cache", None)
        if cache is None:
            cache = self._pp_prim_cache = {}

        def _dispatch(jitted):
            args = list(self._pp_stacked) + [ensure_tensor(x)]
            if use_rng:
                kd = jax.random.key_data(self._pp_generator.next_key())
                args.append(Tensor(kd, _internal=True))
            return apply(jitted, *args, op_name="spmd_pipeline")

        jitted = cache.get(cache_key)
        if jitted is not None:
            return _dispatch(jitted)

        # template layers are unregistered, so train()/eval() doesn't reach
        # them — sync mode explicitly before tracing
        for layer, _ in tpl:
            layer.train() if self.training else layer.eval()
        use_remat = bool(self._recompute_interval) and self.training

        def prim(*arrays):
            if use_rng:
                *stacked, xa, kd = arrays
                base_key = jax.random.wrap_key_data(kd)
            else:
                *stacked, xa = arrays
                base_key = None

            def stage_fn(local, xm, key=None):
                from paddle_tpu.distributed.fleet.pipeline import (
                    functional_rng, template_rng_guard)
                saved = [(t._data, t._grad_node, t._out_slot)
                         for t in tpl_params]
                for t, a in zip(tpl_params, local):
                    t._data = a
                    t._grad_node = None
                ctx = (functional_rng(key) if key is not None else
                       template_rng_guard("the SPMD pipeline stage body"))
                try:
                    with ctx:
                        out = Tensor(xm, _internal=True)
                        for layer, ffunc in tpl:
                            out = ffunc(layer, out) if ffunc is not None \
                                else layer(out)
                        return out._data
                finally:
                    for t, (d, nd, sl) in zip(tpl_params, saved):
                        t._data = d
                        t._grad_node = nd
                        t._out_slot = sl

            if use_remat:
                stage_fn = jax.checkpoint(stage_fn)
            return spmd_pipeline_interleaved(
                stage_fn, n_stages, n_chunks, n_micro, list(stacked), xa,
                mesh, rng_key=base_key)

        # jit so the partial-manual shard_map sees a compiled context even
        # when the surrounding step runs eagerly (sharding inference for the
        # non-manual axes needs it); cached per (mesh, n_micro, mode)
        jitted = jax.jit(prim)
        cache[cache_key] = jitted
        return _dispatch(jitted)

    def forward(self, x):
        from paddle_tpu.distributed.fleet.recompute import recompute
        if self._pp_mode and self._pp_hetero:
            # heterogeneous engine spans the WHOLE layer list (the segment
            # bounds are the stages) — no sequential prefix/suffix
            return self._run_hetero_pipeline(x)
        if self._pp_mode:
            start, end = self._pp_run
            runs = (self.run_funcs[:start]
                    + [None]                        # pipelined region marker
                    + self.run_funcs[end:])
        else:
            runs = self.run_funcs
        for i, entry in enumerate(runs):
            if entry is None:
                x = self._run_spmd_pipeline(x)
                continue
            layer, ffunc = entry
            fn = (lambda inp, _l=layer, _f=ffunc:
                  _f(_l, inp) if _f is not None else _l(inp))
            if self._recompute_interval and i % self._recompute_interval == 0 \
                    and self.training:
                x = recompute(fn, x)
            else:
                x = fn(x)
        return x


class PipelineParallel(Layer):
    """Pipeline runtime (ref `pipeline_parallel.py:33`): `train_batch` splits the
    batch into micro-batches and accumulates grads — identical loss semantics to
    the reference's 1F1B (`forward_backward_pipeline` :119), with XLA scheduling
    the overlap. Use `to_static` around train_batch for the compiled path."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        acc = 1
        if strategy is not None:
            acc = strategy.pipeline_configs.get("accumulate_steps", 1)
        self._accumulate_steps = acc

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        from paddle_tpu.ops.manipulation import split
        x, y = data
        n_micro = self._accumulate_steps
        pp_mode = getattr(self._layers, "_pp_mode", False)
        saved_micro = getattr(self._layers, "_pp_micro", None)
        if pp_mode:
            # real SPMD pipeline: micro-batching happens INSIDE the engine
            # (ppermute schedule); one outer fwd/bwd over the full batch.
            # Restored afterwards so eval/forward see their own setting.
            self._layers._pp_micro = n_micro
            n_micro = 1
        losses = []
        micro_xs = split(x, n_micro, axis=0) if n_micro > 1 else [x]
        micro_ys = split(y, n_micro, axis=0) if n_micro > 1 else [y]
        try:
            for mx, my in zip(micro_xs, micro_ys):
                out = self._layers(mx)
                loss_fn = getattr(self._layers, "_loss_fn", None)
                loss = loss_fn(out, my) if loss_fn is not None else out
                scaled = loss / n_micro
                if scaler is not None:
                    scaler.scale(scaled).backward()
                else:
                    scaled.backward()
                losses.append(loss)
        finally:
            if pp_mode:
                self._layers._pp_micro = saved_micro
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        from paddle_tpu.ops.math import add_n
        total = add_n([l.detach() for l in losses])
        return total / n_micro

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        out = self._layers(x)
        loss_fn = getattr(self._layers, "_loss_fn", None)
        if compute_loss and loss_fn is not None:
            return loss_fn(out, y)
        return out

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)


class HybridParallelOptimizer:
    """ref `dygraph_optimizer/hybrid_parallel_optimizer.py:187` — wraps the inner
    optimizer with group-aware grad sync/clip. Grad sync is compiled into the
    program by GSPMD, so this wrapper only preserves API (clip already group-
    correct because grads are global arrays)."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    def minimize(self, *a, **k):
        return self._inner_opt.minimize(*a, **k)
