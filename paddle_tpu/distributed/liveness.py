"""Collective hang watchdog: per-step liveness heartbeats + guarded waits.

A dead peer is the one training failure the runtime cannot surface by
itself: every survivor of a ``kill -9`` sits inside an allreduce waiting
for a contribution that will never arrive — no exception, no timeout the
loop owns, just silence. The serving plane already refuses that shape
(every request terminates with tokens or a typed error, PR 8); this
module gives the TRAINING plane the same contract:

- :class:`LivenessMonitor` — per-rank heartbeat files in a shared
  directory (the same shared-filesystem substrate the elastic
  `NodeRegistry` leases use). Each rank calls ``beat(step)`` once per
  training step — a thread-free write, so a process wedged inside a
  collective stops beating by construction (a daemon-thread heartbeat
  would keep renewing through the hang and defeat the whole point).
  ``check()`` raises a typed :class:`PeerLost` naming every silent rank
  once its heartbeat age passes the deadline, after dumping the
  flight-recorder ring + the stalled-step context to a JSON post-mortem
  (`observability/flight_recorder.py`).
- :func:`guarded_get_bytes` — the coordination-service blocking read,
  sliced into short waits with a ``check()`` between slices: the
  would-be-infinite collective wait converts into ``PeerLost`` on every
  survivor within a bounded window. With no monitor installed the wait
  degrades to the plain single blocking call — zero behavior change for
  single-host runs.
- :func:`kv_barrier` — an arrival barrier over sequenced KV keys (the
  0.4.x-compatible substrate `distributed/collective.py` already uses),
  built on the guarded read so a barrier over a dead fleet also resolves
  typed. `CheckpointManager` uses it to order per-rank shard writes
  before the COMPLETE/LATEST publication (docs/ROBUSTNESS.md
  "Multi-host training").

Metrics: ``train.heartbeats``, ``train.peer_lost`` (docs/OBSERVABILITY.md).
Chaos: ``train.collective_stall`` (a rank stalls inside the collective —
armed via `testing/faults.py` at the allgather site), ``train.peer_dead``
(a rank SIGKILLs itself at a step boundary — `train/elastic.py`).
"""
from __future__ import annotations

import json
import os
import threading
import time

from paddle_tpu.observability import metrics
from paddle_tpu.observability.flight_recorder import dump_ring, flight

__all__ = ["PeerLost", "LivenessMonitor", "install", "uninstall", "current",
           "guarded_get_bytes", "kv_barrier", "kv_barrier_cleanup",
           "is_timeout"]


class PeerLost(RuntimeError):
    """A training peer went silent past the liveness deadline while the
    fleet was inside (or headed into) a collective. The raiser has
    already dumped the flight ring; its job now is to exit nonzero so
    the elastic controller can reform the mesh at the surviving world
    size and resume from the last fleet-complete checkpoint — iterating
    on a dead fleet cannot succeed (docs/ROBUSTNESS.md)."""


# poll period between presence checks; short enough that deadline ->
# typed-error latency is dominated by the deadline itself, long enough
# that a healthy wait costs a handful of RPCs
_POLL_S = 0.2

# marker namespace: every guarded payload key K gets an ASCII sidecar
# ``ptpu_mk/<K>`` set AFTER the payload. Guarded waiters poll the marker's
# parent DIRECTORY via key_value_dir_get (string-valued listing — safe over
# this namespace by construction) and only issue the blocking read once the
# marker is present, so the read returns immediately. This jaxlib's client
# SEGFAULTS (not raises) when blocking gets EXPIRE under cross-process
# concurrency, and its dir_get chokes on binary values — the marker design
# routes around both: no blocking get ever expires, no binary value is
# ever listed.
_MARK = "ptpu_mk/"


class LivenessMonitor:
    """Per-step heartbeat board for one training fleet.

    dir        : shared directory holding ``hb-<rank>.json`` files (the
                 checkpoint root's filesystem — every rank mounts it)
    rank, world: this process's coordinates
    deadline_s : a peer whose newest beat is older than this is LOST
    grace_s    : a peer with NO heartbeat file yet is only lost after
                 this window from monitor construction (fresh processes
                 need import/compile time before their first beat)
    """

    def __init__(self, dir, rank, world, *, deadline_s=30.0, grace_s=None):
        self.dir = str(dir)
        self.rank = int(rank)
        self.world = int(world)
        self.deadline_s = float(deadline_s)
        self.grace_s = float(grace_s) if grace_s is not None \
            else max(120.0, 4 * self.deadline_s)
        self._born = time.time()
        self.last_step = -1
        os.makedirs(self.dir, exist_ok=True)
        self._g_beats = metrics.counter("train.heartbeats")

    def _path(self, rank):
        return os.path.join(self.dir, f"hb-{rank}.json")

    def beat(self, step):
        """Record this rank's liveness at a step boundary (atomic write —
        a reader never sees a torn file)."""
        self.last_step = int(step)
        tmp = self._path(self.rank) + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"rank": self.rank, "step": int(step),
                       "t": time.time()}, f)
        os.replace(tmp, self._path(self.rank))
        self._g_beats.inc()

    def rebeat(self):
        """Renew the heartbeat WITHOUT claiming progress (same step).
        Guarded waits call this each poll: a rank alive-but-waiting on a
        dead peer must not itself read as dead to the OTHER survivors —
        liveness is "process responsive", the flight watchdog owns
        "progress stalled"."""
        self.beat(self.last_step)

    def peers(self):
        """{rank: {"step", "t", "age_s"}} for every OTHER rank with a
        readable heartbeat file NEWER than this monitor's birth — a beat
        from before we existed is a stale file from a previous fleet
        incarnation, not a peer that died on us: it reads as ABSENT (the
        startup grace window governs it), so a reused heartbeat dir can
        never insta-kill a relaunched fleet."""
        now = time.time()
        out = {}
        for r in range(self.world):
            if r == self.rank:
                continue
            try:
                with open(self._path(r)) as f:
                    info = json.load(f)
                if float(info.get("t", 0.0)) < self._born:
                    continue           # pre-birth: a previous incarnation
                out[r] = {"step": info.get("step"), "t": info.get("t"),
                          "age_s": now - float(info.get("t", 0.0))}
            except (OSError, ValueError):
                continue
        return out

    def silent_peers(self):
        """Ranks whose heartbeat is stale past the deadline (or absent
        past the startup grace window)."""
        peers = self.peers()
        silent = []
        now = time.time()
        for r in range(self.world):
            if r == self.rank:
                continue
            info = peers.get(r)
            if info is None:
                if now - self._born > self.grace_s:
                    silent.append(r)
                continue
            if info["age_s"] > self.deadline_s:
                silent.append(r)
        return silent

    # ------------------------------------------------------- lost cascade
    #
    # The FIRST detector writes a ``lost-<rank>.json`` tombstone before it
    # raises; every other survivor's next check sees it and raises typed
    # WITHOUT waiting out its own deadline. Fast propagation is load-
    # bearing, not a nicety: the coordination service lives in rank 0's
    # process, and this jaxlib's client FATALLY TERMINATES (SIGABRT) any
    # process whose service connection drops — so survivors must all
    # reach their typed exit within a beat of each other, and the leader
    # lingers (`wait_for_cascade`) until the fleet has acknowledged.

    def mark_lost(self, silent):
        """Publish this rank's PeerLost verdict as a tombstone file."""
        tmp = os.path.join(self.dir,
                           f"lost-{self.rank}.json.tmp{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump({"rank": self.rank, "silent": list(silent),
                       "step": self.last_step, "t": time.time()}, f)
        os.replace(tmp, os.path.join(self.dir, f"lost-{self.rank}.json"))

    def lost_peers(self):
        """Ranks (other than self) that published a PeerLost tombstone
        SINCE this monitor was born — like stale heartbeats, a previous
        incarnation's tombstones must not cascade into a relaunched
        fleet."""
        out = []
        for r in range(self.world):
            if r == self.rank:
                continue
            try:
                with open(os.path.join(self.dir, f"lost-{r}.json")) as f:
                    info = json.load(f)
            except (OSError, ValueError):
                continue
            if float(info.get("t", 0.0)) >= self._born:
                out.append(r)
        return out

    def wait_for_cascade(self, cap_s=None):
        """Block until every OTHER rank is accounted for — silent (dead)
        or tombstoned (exited typed) — capped at ``cap_s`` (default the
        deadline + slack). The fleet leader calls this before its own
        exit so laggard survivors are not hard-killed mid-detection by
        the coordination-service teardown."""
        cap = time.time() + (cap_s if cap_s is not None
                             else self.deadline_s + 3.0)
        rest = set(range(self.world)) - {self.rank}
        while time.time() < cap:
            if rest <= set(self.silent_peers()) | set(self.lost_peers()):
                return True
            time.sleep(0.1)
        return False

    def check(self, context=""):
        """Raise typed :class:`PeerLost` if any peer is silent past the
        deadline (or has published a PeerLost tombstone) — after writing
        this rank's own tombstone and dumping the flight ring + the
        stalled-step context (the post-mortem a hang never writes for
        itself)."""
        silent = self.silent_peers()
        cascade = self.lost_peers()
        if not silent and not cascade:
            return
        peers = self.peers()
        detail = {r: ({"step": peers[r]["step"],
                       "age_s": round(peers[r]["age_s"], 1)}
                      if r in peers else "no heartbeat") for r in silent}
        try:
            self.mark_lost(silent or cascade)
        except OSError:
            pass
        metrics.counter("train.peer_lost").inc()
        flight.record("train.peer_lost", rank=self.rank,
                      silent=list(silent), cascade=list(cascade),
                      at_step=self.last_step, context=str(context)[:120])
        path = None
        try:
            path = dump_ring(
                f"peer_lost_rank{self.rank}",
                stalled_step=self.last_step, silent_peers=detail,
                cascade_from=list(cascade),
                deadline_s=self.deadline_s, context=str(context)[:200])
        except OSError:
            pass                   # an unwritable dump dir must not mask
        via = (f"peer(s) {silent} silent past {self.deadline_s}s"
               if silent else f"peer(s) {cascade} reported PeerLost")
        raise PeerLost(
            f"rank {self.rank}: {via} at step {self.last_step}"
            f"{' in ' + context if context else ''} — last heartbeats "
            f"{detail}" + (f" (flight ring dumped to {path})" if path
                           else ""))


# ---------------------------------------------------------- installed hook
#
# collective.py's KV transport consults the installed monitor between wait
# slices; install/uninstall from the elastic worker loop. A lock guards the
# slot itself, not the monitor (beats/checks are single-threaded per rank).

_lock = threading.Lock()
_monitor: LivenessMonitor | None = None


def install(monitor: LivenessMonitor):
    global _monitor
    with _lock:
        _monitor = monitor
    return monitor


def uninstall():
    global _monitor
    with _lock:
        _monitor = None


def current() -> LivenessMonitor | None:
    return _monitor


def is_timeout(exc) -> bool:
    """True for a coordination-service deadline expiry (the 0.4.x client
    raises a generic XlaRuntimeError — the string is the only contract)
    or this module's own TimeoutError."""
    s = str(exc)
    return "DEADLINE_EXCEEDED" in s or "timed out" in s.lower()


def set_with_marker(client, key, value):
    """Publish ``key`` then its readiness marker — the setter half of the
    guarded-read protocol. Guarded waiters poll the marker; plain waiters
    (no monitor) ignore it. Marker-after-payload ordering is the whole
    contract: the set RPCs are synchronous, so a visible marker implies a
    readable payload."""
    client.key_value_set_bytes(key, value)
    client.key_value_set_bytes(_MARK + key, b"1")


def clear_with_marker(client, key):
    """Best-effort delete of a payload and its marker."""
    for k in (key, _MARK + key):
        try:
            client.key_value_delete(k)
        except Exception:  # noqa: BLE001 — cleanup is best-effort
            pass


def _marker_present(client, key) -> bool:
    marker = _MARK + key
    prefix = marker.rsplit("/", 1)[0] + "/"
    try:
        names = {k for k, _ in client.key_value_dir_get(prefix)}
    except Exception:  # noqa: BLE001 — transient listing failure: not there
        return False
    return marker in names


def guarded_get_bytes(client, key, timeout_ms, *, monitor=None, what=""):
    """``blocking_key_value_get_bytes`` with the liveness guard.

    No monitor (installed or passed): one plain blocking call — byte-for-
    byte the pre-guard behavior. With a monitor: poll the key's readiness
    MARKER (see module docstring) with a ``check()`` between polls, so a
    read whose WRITER died resolves as typed ``PeerLost`` within
    ~deadline; only once the marker is present does the blocking read
    run — and then it returns immediately. The writer must publish via
    :func:`set_with_marker`."""
    m = monitor if monitor is not None else current()
    if m is None:
        return client.blocking_key_value_get_bytes(key, int(timeout_ms))
    deadline = time.monotonic() + timeout_ms / 1e3
    while True:
        if _marker_present(client, key):
            return client.blocking_key_value_get_bytes(key, 30_000)
        m.rebeat()
        m.check(context=what or key)
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"KV read {key!r} timed out after {timeout_ms}ms with all "
                "peers still heartbeating")
        time.sleep(_POLL_S)


def kv_barrier(client, tag, *, rank, world, timeout_ms, monitor=None):
    """Arrival barrier over the coordination-service KV store.

    Every rank publishes ``ptpu_bar/<tag>/<rank>`` then POLLS the tag's
    directory listing until all ``world`` arrival keys are present:
    returns once the fleet arrived, raises typed ``PeerLost`` (via the
    monitor, when one is installed/passed) when a peer never does, plain
    TimeoutError otherwise. Pure polling — unlike the service's one-shot
    ``wait_at_barrier`` it composes with the liveness guard and never
    issues an expiring blocking read (see module docstring). Tags must be
    UNIQUE per rendezvous (keys are write-once); cleanup is deliberately
    deferred — a rank that passed barrier N may still be listing when
    another rank moves on, so only a LATER rendezvous proves everyone is
    past this one. Call :func:`kv_barrier_cleanup` with a tag from a
    previous, fully superseded rendezvous (`CheckpointManager` cleans
    save N-1's tags after save N's first barrier)."""
    world = int(world)
    if world <= 1:
        return
    m = monitor if monitor is not None else current()
    prefix = f"ptpu_bar/{tag}/"
    client.key_value_set_bytes(prefix + str(int(rank)), b"1")
    expected = {prefix + str(r) for r in range(world)}
    deadline = time.monotonic() + timeout_ms / 1e3
    while True:
        try:
            names = {k for k, _ in client.key_value_dir_get(prefix)}
        except Exception:  # noqa: BLE001 — transient listing failure
            names = set()
        if expected <= names:
            return
        if m is not None:
            m.rebeat()
            m.check(context=f"barrier {tag}")
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"barrier {tag!r} timed out after {timeout_ms}ms: "
                f"{sorted(expected - names)} never arrived")
        time.sleep(_POLL_S)


def kv_barrier_cleanup(client, tag):
    """Best-effort prefix delete of a SUPERSEDED barrier's keys (see
    :func:`kv_barrier` for when that is safe)."""
    try:
        client.key_value_delete(f"ptpu_bar/{tag}/")
    except Exception:  # noqa: BLE001 — cleanup is best-effort
        pass
