"""paddle.distributed.spawn (ref: `python/paddle/distributed/spawn.py`).

On TPU a host owns all its local chips through one process, so the common case is
nprocs=1 with in-process multi-device parallelism; multi-host spawn delegates to
the launch module's pod builder.
"""
from __future__ import annotations

import multiprocessing as mp
import os


def _worker(func, rank, nprocs, args):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    func(*args)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    if nprocs <= 1:
        func(*args)
        return None
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker, args=(func, rank, nprocs, args),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(f"spawned rank failed with {p.exitcode}")
    return procs
