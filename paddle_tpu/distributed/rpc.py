"""``paddle.distributed.rpc`` — worker-to-worker remote procedure calls.

Rebuild of the reference's brpc-based RPC tower
(`paddle/fluid/distributed/rpc/rpc_agent.cc`, python surface
`python/paddle/distributed/rpc/rpc.py`: init_rpc, rpc_sync :114,
rpc_async :157, shutdown, get_worker_info). brpc collapses to a plain TCP
JSON-length-prefixed pickle protocol: every worker runs a daemon server
thread; calls pickle (fn, args, kwargs), the callee executes and ships the
result back. The master (worker 0 or an external store) performs name →
(host, port) rendezvous exactly like the reference's KVStore handshake.

Security model: RPC executes pickled callables, so it is for TRUSTED cluster
networks only (the same assumption as the reference's brpc agents). Defense
in depth: the agent binds the advertised interface (not 0.0.0.0), and every
connection must open with a 32-byte shared-secret digest — set
``PADDLE_RPC_TOKEN`` to a cluster secret, else one is derived from the master
endpoint (which only guards against accidental cross-job connections, not an
attacker on the same network) — before any pickle is read off the wire.
"""
from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import socket
import struct
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown", "get_worker_info",
           "get_all_worker_infos", "get_current_worker_info", "WorkerInfo"]


@dataclass
class WorkerInfo:
    name: str
    rank: int
    ip: str
    port: int


_state = None
_AUTH_LEN = 32


def _auth_token(master_endpoint: str) -> bytes:
    secret = os.environ.get("PADDLE_RPC_TOKEN") or f"pt-rpc:{master_endpoint}"
    return hashlib.sha256(secret.encode()).digest()


def _check_auth(conn, token: bytes) -> bool:
    """Read exactly the 32-byte preamble and compare; nothing is unpickled
    from an unauthenticated peer."""
    got = b""
    try:
        while len(got) < _AUTH_LEN:
            chunk = conn.recv(_AUTH_LEN - len(got))
            if not chunk:
                return False
            got += chunk
    except OSError:
        return False
    return hmac.compare_digest(got, token)


def _advertise_ip(master_ip):
    """The address peers should dial: loopback for single-host jobs, else the
    interface that routes to the master (multi-host)."""
    if master_ip in ("127.0.0.1", "localhost"):
        return "127.0.0.1"
    probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        probe.connect((master_ip, 9))  # no traffic sent for UDP connect
        return probe.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        probe.close()


def _send_msg(sock, obj):
    data = pickle.dumps(obj)
    sock.sendall(struct.pack("<Q", len(data)) + data)


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return pickle.loads(bytes(buf))


class _Server(threading.Thread):
    def __init__(self, sock, token):
        super().__init__(daemon=True)
        self._sock = sock
        self._token = token
        self._stop = threading.Event()

    def run(self):
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            with conn:
                conn.settimeout(10)
                if not _check_auth(conn, self._token):
                    return
                conn.settimeout(None)
                while True:
                    msg = _recv_msg(conn)
                    kind = msg[0]
                    if kind == "call":
                        _, fn, args, kwargs = msg
                        try:
                            res = ("ok", fn(*args, **kwargs))
                        except Exception as e:  # ship the exception back
                            res = ("err", e)
                        _send_msg(conn, res)
                    elif kind == "bye":
                        _send_msg(conn, ("ok", None))
                        return
        except (ConnectionError, EOFError, OSError):
            return

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class _RpcState:
    def __init__(self, name, rank, world_size, server_sock, master_addr):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.token = _auth_token(master_addr)
        self.server = _Server(server_sock, self.token)
        self.server.start()
        self.master_addr = master_addr
        self.workers: dict[str, WorkerInfo] = {}
        self.pool = ThreadPoolExecutor(max_workers=8)
        self._conns: dict[str, socket.socket] = {}
        self._conn_lock = threading.Lock()
        self._peer_locks: dict[str, threading.Lock] = {}

    def connect(self, to: str):
        """Returns (socket, per-peer lock): calls to different peers run
        concurrently; calls to one peer serialize on its connection. Dial +
        handshake happen under the PEER lock only, so one unreachable peer
        cannot stall calls to healthy ones."""
        with self._conn_lock:
            lock = self._peer_locks.setdefault(to, threading.Lock())
        with lock:
            if to not in self._conns:
                wi = self.workers[to]
                s = socket.create_connection((wi.ip, wi.port), timeout=60)
                s.sendall(self.token)
                self._conns[to] = s
        return self._conns[to], lock


def _master_rendezvous(state, ip, port, master_ip, master_port):
    """Worker 0 hosts a registry socket; everyone registers then receives the
    full table (ref KVStore barrier in `rpc.py:init_rpc`)."""
    me = WorkerInfo(state.name, state.rank, ip, port)
    if state.rank == 0:
        reg = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        reg.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        reg.bind((master_ip, master_port))
        reg.listen(state.world_size)
        infos = {me.name: me}
        conns = []
        while len(infos) < state.world_size:
            conn, peer = reg.accept()
            conn.settimeout(10)
            if not _check_auth(conn, state.token):
                # loud: a token mismatch (different PADDLE_RPC_TOKEN or a
                # differently-spelled master endpoint) would otherwise hang
                # rendezvous with zero diagnostics
                import logging
                logging.getLogger("paddle_tpu.rpc").warning(
                    "rpc rendezvous: rejected unauthenticated peer %s "
                    "(PADDLE_RPC_TOKEN / master endpoint mismatch?)", peer)
                conn.close()
                continue
            conn.settimeout(None)
            wi = _recv_msg(conn)
            infos[wi.name] = wi
            conns.append(conn)
        for conn in conns:
            _send_msg(conn, infos)
            conn.close()
        reg.close()
        state.workers = infos
    else:
        for _ in range(100):
            try:
                s = socket.create_connection((master_ip, master_port),
                                             timeout=5)
                break
            except OSError:
                time.sleep(0.1)
        else:
            raise ConnectionError("cannot reach rpc master")
        with s:
            s.sendall(state.token)
            _send_msg(s, me)
            state.workers = _recv_msg(s)


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start this worker's RPC agent and rendezvous with the others
    (ref `python/paddle/distributed/rpc/rpc.py:init_rpc`)."""
    global _state, _barrier_count
    import os
    # fresh barrier per agent lifetime (repeated init/shutdown cycles)
    with _barrier_lock:
        _barrier_count = 0
        _barrier_event.clear()
    if rank is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    if world_size is None:
        world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    if master_endpoint is None:
        master_endpoint = os.environ.get("PADDLE_MASTER_ENDPOINT",
                                         "127.0.0.1:29531")
    master_ip, master_port = master_endpoint.rsplit(":", 1)
    ip = _advertise_ip(master_ip)
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    # bind the advertised interface only — the agent executes pickled
    # callables and must not listen on every interface
    try:
        srv.bind((ip, 0))
    except OSError:
        # _advertise_ip's DNS fallback can return a non-local address
        # (stale /etc/hosts, NAT); the auth preamble still gates every
        # connection, so a wildcard bind is an acceptable last resort
        import logging
        logging.getLogger("paddle_tpu.rpc").warning(
            "rpc: cannot bind advertised ip %s, falling back to 0.0.0.0", ip)
        srv.bind(("0.0.0.0", 0))
    srv.listen(64)
    port = srv.getsockname()[1]
    _state = _RpcState(name, rank, world_size, srv, master_endpoint)
    _master_rendezvous(_state, ip, port, master_ip, int(master_port) + 1)
    return get_current_worker_info()


def _require_state():
    if _state is None:
        raise RuntimeError("rpc is not initialized; call init_rpc first")
    return _state


def rpc_sync(to, fn, args=None, kwargs=None, timeout=None):
    """Blocking remote call (ref rpc.py:114)."""
    return rpc_async(to, fn, args=args, kwargs=kwargs,
                     timeout=timeout).result(timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=None) -> Future:
    """Non-blocking remote call returning a Future (ref rpc.py:157).

    The returned future's ``wait()`` alias matches the reference API.
    """
    st = _require_state()

    def do_call():
        conn, lock = st.connect(to)
        with lock:
            _send_msg(conn, ("call", fn, tuple(args or ()), dict(kwargs or {})))
            status, payload = _recv_msg(conn)
        if status == "err":
            raise payload
        return payload

    fut = st.pool.submit(do_call)
    if not hasattr(fut, "wait"):
        fut.wait = fut.result
    return fut


def get_worker_info(name):
    """ref rpc.py:get_worker_info."""
    return _require_state().workers[name]


def get_all_worker_infos():
    st = _require_state()
    return sorted(st.workers.values(), key=lambda w: w.rank)


def get_current_worker_info():
    st = _require_state()
    return st.workers[st.name]


_barrier_lock = threading.Lock()
_barrier_count = 0
_barrier_event = threading.Event()


def _barrier_enter(world_size):
    """Runs on worker 0 (in a per-connection server thread, so blocking here
    is safe): releases once every worker has checked in."""
    global _barrier_count
    with _barrier_lock:
        _barrier_count += 1
        if _barrier_count >= world_size:
            _barrier_event.set()
    _barrier_event.wait(timeout=60)
    return True


def shutdown():
    """Graceful stop: barrier across workers (so nobody tears the server down
    under a peer's in-flight call — ref rpc.py:shutdown's KVStore barrier),
    then drain connections and stop the agent."""
    global _state
    if _state is None:
        return
    if _state.world_size > 1 and _state.workers:
        root = next(w.name for w in _state.workers.values() if w.rank == 0)
        try:
            if _state.rank == 0:
                _barrier_enter(_state.world_size)
            else:
                rpc_sync(root, _barrier_enter, args=(_state.world_size,),
                         timeout=60)
        except (ConnectionError, OSError):
            pass
    for name, conn in list(_state._conns.items()):
        try:
            _send_msg(conn, ("bye",))
            _recv_msg(conn)
            conn.close()
        except OSError:
            pass
    _state.server.stop()
    _state.pool.shutdown(wait=False)
    _state = None
