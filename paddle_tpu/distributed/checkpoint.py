"""Distributed (sharded) checkpointing.

Counterpart of the reference's distributed checkpoint stack — sharded
save/gather for hybrid models (`incubate/distributed/utils/io/dist_save.py`,
`dist_load.py`) and the auto-parallel cross-plan `converter.py` — built on the
TPU-native principle (SURVEY §5.4): the checkpoint is ONE LOGICAL snapshot of
global arrays, written shard-by-shard, loadable under ANY mesh/parallel plan.
Resharding between plans (the reference's converter) therefore needs no
conversion step: load assembles the logical array and places it under the
target sharding.

Format: a directory with
  index.json               — {key: {shape, dtype, shards: [{file, slices}]}}
  <key>.<shard>.npy        — one file per addressable shard per process

Each process writes only the shards it owns (multi-host writes disjoint files;
rank 0 writes the index). ``async_save`` returns immediately and writes from a
background thread (the reference's auto_checkpoint/async pattern).

Durability (docs/ROBUSTNESS.md "Training fault tolerance"): every shard entry
records a content checksum (blake2b over the exact bytes written) plus a
format version stamp in the index, and ``load_sharded`` VERIFIES both — a
truncated, bit-flipped, or future-format checkpoint is refused with a typed
`CheckpointCorrupt`, a structurally missing one (no index, missing shard
file, coverage gap) with `CheckpointIncomplete`; neither is ever silently
loaded. The crash-consistency protocol on top (LATEST pointer, COMPLETE
markers, retention) lives in `paddle_tpu/train/fault_tolerance.py`.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import threading

import numpy as np
import jax

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.testing import faults

# Bumped when the on-disk layout changes incompatibly. Indexes carry it under
# _META_KEY; loaders refuse a mismatched stamp (a checkpoint written by a
# NEWER format must not be half-understood). Legacy indexes without the stamp
# (pre-checksum checkpoints) still load — they simply skip verification.
CKPT_FORMAT_VERSION = 2
_META_KEY = "__ckpt_meta__"


class CheckpointCorrupt(RuntimeError):
    """The checkpoint's payload fails integrity verification: a shard file
    is truncated or undecodable, its content hash does not match the one
    recorded at save time, its shape disagrees with the index, or the
    index carries an incompatible format-version stamp. Never load it —
    resume from an older complete checkpoint instead."""


class CheckpointIncomplete(RuntimeError):
    """The checkpoint is structurally missing pieces: no index, a shard
    file named by the index is absent, the shards do not cover the full
    array, or (at the manager level) there is no LATEST pointer to resume
    from. Typically a save that crashed partway — by protocol such a
    checkpoint was never published and must be ignored, not repaired."""


def _digest(data: np.ndarray) -> str:
    """Content hash of the EXACT array bytes written to disk (post any
    bf16->f32 widening), so load can verify without re-reading the file.
    Hashes through the buffer protocol — no tobytes() copy, so a multi-GB
    shard costs no transient second allocation on the writer thread."""
    return hashlib.blake2b(np.ascontiguousarray(data).data,
                           digest_size=16).hexdigest()


def _sanitize(key):
    """Filesystem-safe, collision-proof file stem for a state_dict key: the
    readable sanitized name plus a short hash of the RAW key (two distinct
    keys like 'a/b' and 'a_b' must never share shard files)."""
    import hashlib
    h = hashlib.sha1(key.encode()).hexdigest()[:8]
    return re.sub(r"[^A-Za-z0-9_.-]", "_", key) + "-" + h


def _slices_to_json(idx, shape):
    out = []
    for sl, dim in zip(idx, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _is_literal(value) -> bool:
    """True for the non-tensor metadata entries (global_step, cursors...)
    that the index stores as JSON literals — the ONE predicate both
    save_sharded and async_save's partition filter use."""
    return isinstance(value, (int, float, str, bool, type(None))) or (
        isinstance(value, (list, tuple)) and all(
            isinstance(v, (int, float, str, bool)) for v in value))


def shard_owner(key: str, world: int) -> int:
    """Stable owner rank for a checkpoint leaf under key-partitioned
    multi-host saves: a content hash of the RAW key mod world, so every
    rank computes the same partition with zero coordination. Literal
    (metadata) entries are always rank 0's."""
    h = hashlib.blake2b(key.encode(), digest_size=8).digest()
    return int.from_bytes(h, "big") % max(1, int(world))


def save_sharded(state_dict, path, partition=None):
    """Save a (possibly nested) state_dict of Tensors shard-by-shard.

    Every process writes its own addressable shards plus a per-process partial
    index ``index.p<pid>.json``; loaders merge ALL partial indexes, so
    multi-host saves need no cross-process gather or barrier. Writes publish
    atomically (tmp + rename).

    ``partition=(rank, world)``: key-partitioned multi-host mode for fleets
    whose per-process state is fully REPLICATED (eager data-parallel — each
    process holds the whole array): rank r writes only the leaves
    :func:`shard_owner` assigns it (literals go to rank 0), so the fleet
    writes each byte once and the merged indexes cover the full state only
    when EVERY rank's shards landed — a missing rank makes the checkpoint
    structurally incomplete, which is exactly what the manager's
    pre-COMPLETE barrier turns into "complete or invisible" fleet-wide
    (docs/ROBUSTNESS.md "Multi-host training")."""
    os.makedirs(path, exist_ok=True)
    if partition is not None:
        rank, world = int(partition[0]), int(partition[1])
        if not (0 <= rank < world):
            raise ValueError(f"partition rank {rank} outside world {world}")
        pid = rank
    else:
        rank = world = None
        pid = jax.process_index()
    index = {_META_KEY: {"version": CKPT_FORMAT_VERSION}}
    nwritten = 0

    def _write_shard(fname, data):
        # chaos sites (tests/test_train_chaos.py): a save that dies between
        # shard files must leave the checkpoint INVISIBLE (no index, no
        # LATEST), and a torn write must be refused at load by checksum
        nonlocal nwritten
        from paddle_tpu.distributed import liveness
        mon = liveness.current()
        if mon is not None:
            # a rank actively writing shards is ALIVE: renew the heartbeat
            # per shard so a slow shared-filesystem write never reads as a
            # dead peer to ranks already waiting at the publication barrier
            mon.rebeat()
        if faults.ENABLED and nwritten > 0 \
                and faults.fire("ckpt.crash_between_shards"):
            raise faults.FaultInjected(
                f"fault injected at ckpt.crash_between_shards ({fname})")
        fpath = os.path.join(path, fname)
        np.save(fpath, data)
        nwritten += 1
        if faults.ENABLED and faults.fire("ckpt.write_truncate"):
            with open(fpath, "r+b") as f:
                f.truncate(max(1, os.path.getsize(fpath) // 2))

    for key, value in _flatten(state_dict).items():
        if _is_literal(value):
            if world is not None and rank != 0:
                continue               # literals are rank 0's
            # non-tensor metadata (global_step, key manifests...): JSON literal
            index[key] = {"literal": value if not isinstance(value, tuple)
                          else list(value)}
            continue
        if world is not None and shard_owner(key, world) != rank:
            continue                   # another rank writes this leaf
        arr = value._data if isinstance(value, Tensor) else value
        if isinstance(arr, np.ndarray):
            # pre-snapshotted host array (async_save): one full-shape shard
            skey = _sanitize(key)
            dtype = str(arr.dtype)
            data = arr
            fname = f"{skey}.p{pid}s0.npy"
            _write_shard(fname, data)
            index[key] = {"shape": list(arr.shape), "dtype": dtype,
                          "shards": [{"file": fname, "slices": [
                              [0, d] for d in arr.shape],
                              "sum": _digest(data)}]}
            continue
        if not hasattr(arr, "addressable_shards"):
            arr = jax.numpy.asarray(arr)
        skey = _sanitize(key)
        entries = []
        seen = set()
        for j, shard in enumerate(arr.addressable_shards):
            tup = _slices_to_json(shard.index, arr.shape)
            sig = tuple(map(tuple, tup))
            if sig in seen:          # replicated shards: write once
                continue
            seen.add(sig)
            fname = f"{skey}.p{pid}s{j}.npy"
            data = np.asarray(shard.data)
            if str(arr.dtype) == "bfloat16":
                data = data.astype(np.float32)   # npy-portable; dtype in index
            _write_shard(fname, data)
            entries.append({"file": fname, "slices": tup,
                            "sum": _digest(data)})
        index[key] = {"shape": list(arr.shape), "dtype": str(arr.dtype),
                      "shards": entries}
    idx_path = os.path.join(path, f"index.p{pid}.json")
    tmp = idx_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(index, f)
    os.replace(tmp, idx_path)
    if pid == 0:
        # back-compat alias; loaders merge every index.p*.json regardless
        tmp = os.path.join(path, "index.json.tmp")
        with open(tmp, "w") as f:
            json.dump(index, f)
        os.replace(tmp, os.path.join(path, "index.json"))
    return path


class _SaveThread(threading.Thread):
    """Background writer that re-raises its exception on join(). A failed
    write must surface on the NEXT join()/wait(), never vanish in a
    daemon thread — callers (`CheckpointManager`) join before starting
    the next save, so at most one checkpoint interval passes between a
    write failing and the training loop hearing about it."""

    def __init__(self, snapshot, path, on_complete=None, partition=None):
        super().__init__(daemon=True, name="pt-ckpt-save")
        self._snapshot = snapshot
        self._path = path
        self._on_complete = on_complete
        self._partition = partition
        self.exc = None

    def run(self):
        try:
            save_sharded(self._snapshot, self._path,
                         partition=self._partition)
            if self._on_complete is not None:
                self._on_complete(self._path)
        except BaseException as e:   # noqa: BLE001 — stored, re-raised on join
            self.exc = e

    def join(self, timeout=None):
        super().join(timeout)
        if not self.is_alive() and self.exc is not None:
            raise self.exc

    # checkpoint-manager alias: `wait()` = join + error propagation
    wait = join


def async_save(state_dict, path, on_complete=None, partition=None):
    """Copy values to HOST on the calling thread (compiled train steps donate
    the device buffers — a reference would race the next step's in-place
    update), then write in the background. join()/wait() re-raises write
    errors. The blocking cost to the caller is ONLY the host snapshot — the
    step-stall `bench_train_ft` measures. ``on_complete(path)`` runs on the
    writer thread after a fully successful save (the manager's hook for the
    COMPLETE marker + LATEST pointer); its errors propagate like write
    errors. ``partition=(rank, world)`` snapshots (and writes) only this
    rank's key-partition — see :func:`save_sharded`."""
    snapshot = {}
    for key, value in _flatten(state_dict).items():
        if partition is not None:
            rank, world = int(partition[0]), int(partition[1])
            owner = 0 if _is_literal(value) else shard_owner(key, world)
            if owner != rank:
                continue               # unowned: don't even snapshot it
        arr = value._data if isinstance(value, Tensor) else value
        if hasattr(arr, "addressable_shards"):
            arr = np.asarray(arr)      # synchronous host copy
        snapshot[key] = arr

    t = _SaveThread(snapshot, path, on_complete, partition=partition)
    t.start()
    return t


def read_literal(path, key, default=None):
    """Read ONE literal entry (int/str/list metadata) from a checkpoint's
    index without touching any shard — the cheap metadata peek the
    checkpoint manager uses. Returns ``default`` when the index or the
    key is absent/unreadable. Keeps index-format knowledge in this module
    only."""
    import glob as _glob
    out = default
    for pf in sorted(_glob.glob(os.path.join(path, "index.p*.json"))):
        try:
            with open(pf) as f:
                part = json.load(f)
        except Exception:  # noqa: BLE001 — a peek must never raise
            return default
        entry = part.get(key)
        if isinstance(entry, dict) and "literal" in entry:
            out = entry["literal"]
    return out


def load_sharded(path, template=None, return_numpy=False, verify=True):
    """Load a sharded checkpoint into a flat {key: Tensor} dict.

    ``template``: optional {key: Tensor-or-array} (e.g. a freshly built
    model's state_dict under the CURRENT mesh) — loaded arrays adopt each
    template leaf's sharding, which IS the cross-plan reshard (save under
    dp=8, load under dp2 x mp2 x sp2, any layout).

    Integrity: a missing index or shard file raises `CheckpointIncomplete`;
    a truncated/undecodable shard, a shape that disagrees with the index, a
    content-hash mismatch, or an incompatible format-version stamp raises
    `CheckpointCorrupt`. ``verify=False`` skips only the content hashing
    (structural checks always run) — for resumes the default stays on: a
    corrupt checkpoint must be REFUSED, never trained on."""
    import glob as _glob
    index = {}
    partials = sorted(_glob.glob(os.path.join(path, "index.p*.json")))
    if not partials:
        legacy = os.path.join(path, "index.json")
        if not os.path.exists(legacy):
            raise CheckpointIncomplete(
                f"no checkpoint index under {path!r} — save crashed before "
                "publishing, or wrong directory")
        partials = [legacy]
    for pf in partials:
        with open(pf) as f:
            part = json.load(f)
        meta = part.pop(_META_KEY, None)
        if meta is not None and meta.get("version") != CKPT_FORMAT_VERSION:
            raise CheckpointCorrupt(
                f"checkpoint {path!r} has format version "
                f"{meta.get('version')!r}, this reader understands "
                f"{CKPT_FORMAT_VERSION} — refusing to half-interpret it")
        for key, entry in part.items():
            if key in index and "shards" in entry:
                index[key]["shards"].extend(entry["shards"])
            else:
                index[key] = entry
    tpl_flat = _flatten(template) if template is not None else {}
    out = {}
    for key, meta in index.items():
        if "literal" in meta:
            out[key] = meta["literal"]
            continue
        full = np.empty(meta["shape"], dtype=np.dtype(
            meta["dtype"].replace("bfloat16", "float32")))
        cast_bf16 = meta["dtype"] == "bfloat16"
        boxes = []
        for e in meta["shards"]:
            fpath = os.path.join(path, e["file"])
            if not os.path.exists(fpath):
                raise CheckpointIncomplete(
                    f"checkpoint shard {e['file']!r} for {key!r} is missing "
                    f"from {path!r}")
            try:
                data = np.load(fpath, allow_pickle=False)
            except Exception as exc:  # noqa: BLE001 — any decode failure
                raise CheckpointCorrupt(
                    f"checkpoint shard {e['file']!r} for {key!r} is "
                    f"truncated or undecodable: {type(exc).__name__}: {exc}"
                ) from exc
            want = tuple(b - a for a, b in e["slices"])
            if tuple(data.shape) != want:
                raise CheckpointCorrupt(
                    f"checkpoint shard {e['file']!r} for {key!r} has shape "
                    f"{tuple(data.shape)}, index says {want}")
            if verify and e.get("sum") is not None \
                    and _digest(data) != e["sum"]:
                raise CheckpointCorrupt(
                    f"checkpoint shard {e['file']!r} for {key!r} fails its "
                    "content checksum — bit rot or a torn write; refusing "
                    "to load it")
            sl = tuple(slice(a, b) for a, b in e["slices"])
            full[sl] = data.astype(full.dtype) if cast_bf16 else data
            boxes.append([tuple(p) for p in e["slices"]])
        _check_coverage(key, meta["shape"], boxes)
        arr = full
        if cast_bf16:
            import jax.numpy as jnp
            arr = jnp.asarray(full, jnp.bfloat16)
        if return_numpy:
            out[key] = arr
            continue
        tpl = tpl_flat.get(key)
        tpl_arr = tpl._data if isinstance(tpl, Tensor) else tpl
        if tpl_arr is not None and isinstance(
                getattr(tpl_arr, "sharding", None),
                jax.sharding.NamedSharding):
            # adopt the template's mesh placement (the cross-plan reshard);
            # non-mesh params stay UNCOMMITTED so jit may place them freely
            arr = jax.device_put(arr, tpl_arr.sharding)
        else:
            import jax.numpy as jnp
            arr = jnp.asarray(arr)
        t = Tensor(arr, _internal=True)
        t.persistable = True
        out[key] = t
    return out


def _check_coverage(key, shape, boxes):
    """O(#shards^2) arithmetic coverage check: total volume of (deduped,
    non-overlapping) shard boxes must equal the array volume — no O(#elements)
    bool mask (a 1B-param tensor would cost an extra GB just to verify)."""
    total = int(np.prod(shape)) if shape else 1
    boxes = list({tuple(b) for b in boxes})
    vol = 0
    for b in boxes:
        v = 1
        for lo, hi in b:
            v *= hi - lo
        vol += v
    for i, a in enumerate(boxes):
        for b in boxes[i + 1:]:
            if all(lo1 < hi2 and lo2 < hi1
                   for (lo1, hi1), (lo2, hi2) in zip(a, b)):
                raise CheckpointCorrupt(
                    f"checkpoint shards for '{key}' overlap: {a} vs {b}")
    if vol != total:
        raise CheckpointIncomplete(
            f"checkpoint shard files for '{key}' cover {vol} of {total} "
            f"elements of {shape} — incomplete multi-host save?")


def _flatten(d, prefix=""):
    out = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, prefix=f"{key}/"))
        else:
            out[key] = v
    return out
