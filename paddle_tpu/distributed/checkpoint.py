"""Distributed (sharded) checkpointing.

Counterpart of the reference's distributed checkpoint stack — sharded
save/gather for hybrid models (`incubate/distributed/utils/io/dist_save.py`,
`dist_load.py`) and the auto-parallel cross-plan `converter.py` — built on the
TPU-native principle (SURVEY §5.4): the checkpoint is ONE LOGICAL snapshot of
global arrays, written shard-by-shard, loadable under ANY mesh/parallel plan.
Resharding between plans (the reference's converter) therefore needs no
conversion step: load assembles the logical array and places it under the
target sharding.

Format: a directory with
  index.json               — {key: {shape, dtype, shards: [{file, slices}]}}
  <key>.<shard>.npy        — one file per addressable shard per process

Each process writes only the shards it owns (multi-host writes disjoint files;
rank 0 writes the index). ``async_save`` returns immediately and writes from a
background thread (the reference's auto_checkpoint/async pattern).
"""
from __future__ import annotations

import json
import os
import re
import threading

import numpy as np
import jax

from paddle_tpu.core.tensor import Tensor


def _sanitize(key):
    """Filesystem-safe, collision-proof file stem for a state_dict key: the
    readable sanitized name plus a short hash of the RAW key (two distinct
    keys like 'a/b' and 'a_b' must never share shard files)."""
    import hashlib
    h = hashlib.sha1(key.encode()).hexdigest()[:8]
    return re.sub(r"[^A-Za-z0-9_.-]", "_", key) + "-" + h


def _slices_to_json(idx, shape):
    out = []
    for sl, dim in zip(idx, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_sharded(state_dict, path):
    """Save a (possibly nested) state_dict of Tensors shard-by-shard.

    Every process writes its own addressable shards plus a per-process partial
    index ``index.p<pid>.json``; loaders merge ALL partial indexes, so
    multi-host saves need no cross-process gather or barrier. Writes publish
    atomically (tmp + rename)."""
    os.makedirs(path, exist_ok=True)
    pid = jax.process_index()
    index = {}
    for key, value in _flatten(state_dict).items():
        if isinstance(value, (int, float, str, bool, type(None))) or (
                isinstance(value, (list, tuple)) and all(
                    isinstance(v, (int, float, str, bool)) for v in value)):
            # non-tensor metadata (global_step, key manifests...): JSON literal
            index[key] = {"literal": value if not isinstance(value, tuple)
                          else list(value)}
            continue
        arr = value._data if isinstance(value, Tensor) else value
        if isinstance(arr, np.ndarray):
            # pre-snapshotted host array (async_save): one full-shape shard
            skey = _sanitize(key)
            dtype = str(arr.dtype)
            data = arr
            fname = f"{skey}.p{pid}s0.npy"
            np.save(os.path.join(path, fname), data)
            index[key] = {"shape": list(arr.shape), "dtype": dtype,
                          "shards": [{"file": fname, "slices": [
                              [0, d] for d in arr.shape]}]}
            continue
        if not hasattr(arr, "addressable_shards"):
            arr = jax.numpy.asarray(arr)
        skey = _sanitize(key)
        entries = []
        seen = set()
        for j, shard in enumerate(arr.addressable_shards):
            tup = _slices_to_json(shard.index, arr.shape)
            sig = tuple(map(tuple, tup))
            if sig in seen:          # replicated shards: write once
                continue
            seen.add(sig)
            fname = f"{skey}.p{pid}s{j}.npy"
            data = np.asarray(shard.data)
            if str(arr.dtype) == "bfloat16":
                data = data.astype(np.float32)   # npy-portable; dtype in index
            np.save(os.path.join(path, fname), data)
            entries.append({"file": fname, "slices": tup})
        index[key] = {"shape": list(arr.shape), "dtype": str(arr.dtype),
                      "shards": entries}
    idx_path = os.path.join(path, f"index.p{pid}.json")
    tmp = idx_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(index, f)
    os.replace(tmp, idx_path)
    if pid == 0:
        # back-compat alias; loaders merge every index.p*.json regardless
        tmp = os.path.join(path, "index.json.tmp")
        with open(tmp, "w") as f:
            json.dump(index, f)
        os.replace(tmp, os.path.join(path, "index.json"))
    return path


class _SaveThread(threading.Thread):
    """Background writer that re-raises its exception on join()."""

    def __init__(self, snapshot, path):
        super().__init__(daemon=True)
        self._snapshot = snapshot
        self._path = path
        self.exc = None

    def run(self):
        try:
            save_sharded(self._snapshot, self._path)
        except BaseException as e:   # noqa: BLE001 — stored, re-raised on join
            self.exc = e

    def join(self, timeout=None):
        super().join(timeout)
        if not self.is_alive() and self.exc is not None:
            raise self.exc


def async_save(state_dict, path):
    """Copy values to HOST on the calling thread (compiled train steps donate
    the device buffers — a reference would race the next step's in-place
    update), then write in the background. join() re-raises write errors."""
    snapshot = {}
    for key, value in _flatten(state_dict).items():
        arr = value._data if isinstance(value, Tensor) else value
        if hasattr(arr, "addressable_shards"):
            arr = np.asarray(arr)      # synchronous host copy
        snapshot[key] = arr

    t = _SaveThread(snapshot, path)
    t.start()
    return t


def load_sharded(path, template=None, return_numpy=False):
    """Load a sharded checkpoint into a flat {key: Tensor} dict.

    ``template``: optional {key: Tensor} (e.g. a freshly built model's
    state_dict under the CURRENT mesh) — loaded arrays adopt each template
    tensor's sharding, which IS the cross-plan reshard (save under dp=8, load
    under dp2 x mp2 x sp2, any layout)."""
    import glob as _glob
    index = {}
    partials = sorted(_glob.glob(os.path.join(path, "index.p*.json")))
    if not partials:
        partials = [os.path.join(path, "index.json")]
    for pf in partials:
        with open(pf) as f:
            part = json.load(f)
        for key, meta in part.items():
            if key in index and "shards" in meta:
                index[key]["shards"].extend(meta["shards"])
            else:
                index[key] = meta
    tpl_flat = _flatten(template) if template is not None else {}
    out = {}
    for key, meta in index.items():
        if "literal" in meta:
            out[key] = meta["literal"]
            continue
        full = np.empty(meta["shape"], dtype=np.dtype(
            meta["dtype"].replace("bfloat16", "float32")))
        cast_bf16 = meta["dtype"] == "bfloat16"
        boxes = []
        for e in meta["shards"]:
            data = np.load(os.path.join(path, e["file"]),
                           allow_pickle=False)
            sl = tuple(slice(a, b) for a, b in e["slices"])
            full[sl] = data.astype(full.dtype) if cast_bf16 else data
            boxes.append([tuple(p) for p in e["slices"]])
        _check_coverage(key, meta["shape"], boxes)
        arr = full
        if cast_bf16:
            import jax.numpy as jnp
            arr = jnp.asarray(full, jnp.bfloat16)
        if return_numpy:
            out[key] = arr
            continue
        tpl = tpl_flat.get(key)
        if tpl is not None and isinstance(
                getattr(tpl._data, "sharding", None),
                jax.sharding.NamedSharding):
            # adopt the template's mesh placement (the cross-plan reshard);
            # non-mesh params stay UNCOMMITTED so jit may place them freely
            arr = jax.device_put(arr, tpl._data.sharding)
        else:
            import jax.numpy as jnp
            arr = jnp.asarray(arr)
        t = Tensor(arr, _internal=True)
        t.persistable = True
        out[key] = t
    return out


def _check_coverage(key, shape, boxes):
    """O(#shards^2) arithmetic coverage check: total volume of (deduped,
    non-overlapping) shard boxes must equal the array volume — no O(#elements)
    bool mask (a 1B-param tensor would cost an extra GB just to verify)."""
    total = int(np.prod(shape)) if shape else 1
    boxes = list({tuple(b) for b in boxes})
    vol = 0
    for b in boxes:
        v = 1
        for lo, hi in b:
            v *= hi - lo
        vol += v
    for i, a in enumerate(boxes):
        for b in boxes[i + 1:]:
            if all(lo1 < hi2 and lo2 < hi1
                   for (lo1, hi1), (lo2, hi2) in zip(a, b)):
                raise ValueError(
                    f"checkpoint shards for '{key}' overlap: {a} vs {b}")
    if vol != total:
        raise ValueError(
            f"checkpoint shard files for '{key}' cover {vol} of {total} "
            f"elements of {shape} — incomplete multi-host save?")


def _flatten(d, prefix=""):
    out = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, prefix=f"{key}/"))
        else:
            out[key] = v
    return out
