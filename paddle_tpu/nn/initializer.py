"""Weight initializers (ref: `python/paddle/nn/initializer/`).

Each initializer is a callable ``(shape, dtype) -> jax array`` evaluated eagerly at
parameter creation (the reference appends init ops to the startup program; with no
static graph the array is just computed).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.ops.random import default_generator


def _fan_in_out(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle Linear weights are [in, out]
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv weights are [out_c, in_c/groups, *k]
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        key = default_generator().next_key()
        return self.mean + self.std * jax.random.normal(key, shape, dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        key = default_generator().next_key()
        return self.mean + self.std * jax.random.truncated_normal(
            key, self.a, self.b, shape, dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        key = default_generator().next_key()
        return jax.random.uniform(key, shape, dtype, self.low, self.high)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        key = default_generator().next_key()
        return jax.random.uniform(key, shape, dtype, -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        key = default_generator().next_key()
        return std * jax.random.normal(key, shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _gain(self):
        if self.nonlinearity == "leaky_relu":
            return math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        return math.sqrt(2.0)

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        limit = self._gain() * math.sqrt(3.0 / fi)
        key = default_generator().next_key()
        return jax.random.uniform(key, shape, dtype, -limit, limit)


class KaimingNormal(KaimingUniform):
    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        std = self._gain() / math.sqrt(fi)
        key = default_generator().next_key()
        return std * jax.random.normal(key, shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from paddle_tpu.core.tensor import Tensor
        v = self.value
        arr = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return arr.astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        mins = min(oc // self.groups, ic)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(mins):
                idx = (g * (oc // self.groups) + i, i) + tuple(centers)
                out[idx] = 1.0
        return jnp.asarray(out, dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        key = default_generator().next_key()
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = jax.random.normal(key, (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0
