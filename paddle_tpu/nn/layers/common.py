"""Common layers: Linear, Embedding, Dropout, Flatten, Pad, Upsample, Bilinear...
(ref: `python/paddle/nn/layer/common.py`)."""
from __future__ import annotations

import math

import jax.numpy as jnp

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn import functional as F
from paddle_tpu.core.tensor import Parameter, Tensor
from paddle_tpu.framework.param_attr import ParamAttr


class Linear(Layer):
    """y = xW + b with W: [in_features, out_features] (paddle convention,
    ref `python/paddle/nn/layer/common.py` Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=ParamAttr._to_attr(weight_attr))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_features,), attr=ParamAttr._to_attr(bias_attr), is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx if padding_idx is None or padding_idx >= 0 \
            else num_embeddings + padding_idx
        attr = ParamAttr._to_attr(weight_attr)
        if attr is None or (isinstance(attr, ParamAttr) and attr.initializer is None):
            init = I.Normal(0.0, 1.0)
            if isinstance(attr, ParamAttr):
                attr.initializer = init
            else:
                attr = ParamAttr(initializer=init)
        self.weight = self.create_parameter((num_embeddings, embedding_dim),
                                            attr=attr)
        if self._padding_idx is not None:
            with_pad = self.weight._data.at[self._padding_idx].set(0.0)
            self.weight._write(with_pad)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from paddle_tpu.ops.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        from paddle_tpu.ops.manipulation import reshape
        shp = list(x.shape)
        shp[self.axis: self.axis + 1] = list(self.shape)
        return reshape(x, shp)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features),
            attr=ParamAttr._to_attr(weight_attr))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_features,), attr=ParamAttr._to_attr(bias_attr), is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        from paddle_tpu.core.autograd import apply
        return apply(lambda a, b: jnp.sum(
            jnp.abs(a - b + self.epsilon) ** self.p, axis=-1,
            keepdims=self.keepdim) ** (1.0 / self.p), x, y,
            op_name="pairwise_distance")


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    pass


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(_PadNd):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)
