"""RNN layers (ref: `python/paddle/nn/layer/rnn.py` — RNNCellBase, SimpleRNNCell,
LSTMCell, GRUCell, RNN, SimpleRNN, LSTM, GRU). Recurrence is a `lax.scan` inside one
traced op, which XLA unrolls/fuses — no per-step python dispatch in the hot path.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import initializer as I
from paddle_tpu.framework.param_attr import ParamAttr
from paddle_tpu.ops.common import ensure_tensor


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0,
                           batch_dim_idx=0):
        from paddle_tpu.ops.creation import full
        B = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape, (list, tuple)) and isinstance(shape[0], (list, tuple)):
            return tuple(full([B] + list(s), init_value,
                              dtype or batch_ref.dtype) for s in shape)
        return full([B] + list(shape), init_value, dtype or batch_ref.dtype)


def _uniform_attr(attr, hidden_size):
    std = 1.0 / math.sqrt(hidden_size)
    a = ParamAttr._to_attr(attr)
    if a is None:
        return ParamAttr(initializer=I.Uniform(-std, std))
    if isinstance(a, ParamAttr) and a.initializer is None:
        a.initializer = I.Uniform(-std, std)
    return a


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        self.weight_ih = self.create_parameter(
            (hidden_size, input_size), attr=_uniform_attr(weight_ih_attr,
                                                          hidden_size))
        self.weight_hh = self.create_parameter(
            (hidden_size, hidden_size), attr=_uniform_attr(weight_hh_attr,
                                                           hidden_size))
        self.bias_ih = self.create_parameter(
            (hidden_size,), attr=_uniform_attr(bias_ih_attr, hidden_size),
            is_bias=True)
        self.bias_hh = self.create_parameter(
            (hidden_size,), attr=_uniform_attr(bias_hh_attr, hidden_size),
            is_bias=True)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def prim(x, h, wi, wh, bi, bh):
            out = act(x @ wi.T + bi + h @ wh.T + bh)
            return out

        h = apply(prim, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, op_name="simple_rnn_cell")
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.weight_ih = self.create_parameter(
            (4 * hidden_size, input_size),
            attr=_uniform_attr(weight_ih_attr, hidden_size))
        self.weight_hh = self.create_parameter(
            (4 * hidden_size, hidden_size),
            attr=_uniform_attr(weight_hh_attr, hidden_size))
        self.bias_ih = self.create_parameter(
            (4 * hidden_size,), attr=_uniform_attr(bias_ih_attr, hidden_size),
            is_bias=True)
        self.bias_hh = self.create_parameter(
            (4 * hidden_size,), attr=_uniform_attr(bias_hh_attr, hidden_size),
            is_bias=True)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        h0, c0 = states

        def prim(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            new_c = f * c + i * g
            new_h = o * jnp.tanh(new_c)
            return new_h, new_c

        new_h, new_c = apply(prim, inputs, h0, c0, self.weight_ih, self.weight_hh,
                             self.bias_ih, self.bias_hh, op_name="lstm_cell")
        return new_h, (new_h, new_c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.weight_ih = self.create_parameter(
            (3 * hidden_size, input_size),
            attr=_uniform_attr(weight_ih_attr, hidden_size))
        self.weight_hh = self.create_parameter(
            (3 * hidden_size, hidden_size),
            attr=_uniform_attr(weight_hh_attr, hidden_size))
        self.bias_ih = self.create_parameter(
            (3 * hidden_size,), attr=_uniform_attr(bias_ih_attr, hidden_size),
            is_bias=True)
        self.bias_hh = self.create_parameter(
            (3 * hidden_size,), attr=_uniform_attr(bias_hh_attr, hidden_size),
            is_bias=True)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def prim(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h

        h = apply(prim, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, op_name="gru_cell")
        return h, h


class RNN(Layer):
    """Wrap a cell into a scan over time (ref rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        # simple per-step python loop through the cell keeps arbitrary cells
        # correct; the fused multi-layer classes below use one lax.scan instead.
        from paddle_tpu.ops.manipulation import stack, flip
        time_axis = 0 if self.time_major else 1
        steps = inputs.shape[time_axis]
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        states = initial_states
        outs = []
        for t in order:
            xt = inputs[t] if self.time_major else inputs[:, t]
            out, states = self.cell(xt, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        outputs = stack(outs, axis=time_axis)
        return outputs, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from paddle_tpu.ops.manipulation import concat
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, s_fw, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, s_bw, sequence_length)
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    """Fused multi-layer multi-direction RNN executed as lax.scan per layer."""

    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.num_directions = 2 if direction in ("bidirect", "bidirectional") else 1
        g = {"LSTM": 4, "GRU": 3}.get(self.MODE, 1)
        self._gates = g
        self.weight_ih_list = []
        self.weight_hh_list = []
        self.bias_ih_list = []
        self.bias_hh_list = []
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_sz = input_size if layer == 0 else \
                    hidden_size * self.num_directions
                wi = self.create_parameter(
                    (g * hidden_size, in_sz),
                    attr=_uniform_attr(weight_ih_attr, hidden_size))
                wh = self.create_parameter(
                    (g * hidden_size, hidden_size),
                    attr=_uniform_attr(weight_hh_attr, hidden_size))
                bi = self.create_parameter(
                    (g * hidden_size,),
                    attr=_uniform_attr(bias_ih_attr, hidden_size), is_bias=True)
                bh = self.create_parameter(
                    (g * hidden_size,),
                    attr=_uniform_attr(bias_hh_attr, hidden_size), is_bias=True)
                sfx = f"{layer}" + ("_reverse" if d else "")
                self.add_parameter(f"weight_ih_l{sfx}", wi)
                self.add_parameter(f"weight_hh_l{sfx}", wh)
                self.add_parameter(f"bias_ih_l{sfx}", bi)
                self.add_parameter(f"bias_hh_l{sfx}", bh)
                self.weight_ih_list.append(wi)
                self.weight_hh_list.append(wh)
                self.bias_ih_list.append(bi)
                self.bias_hh_list.append(bh)

    def _cell_step(self, mode):
        if mode == "LSTM":
            def step(x, hc, wi, wh, bi, bh):
                h, c = hc
                gates = x @ wi.T + bi + h @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i, f, o = (jax.nn.sigmoid(i), jax.nn.sigmoid(f),
                           jax.nn.sigmoid(o))
                g = jnp.tanh(g)
                c = f * c + i * g
                h = o * jnp.tanh(c)
                return h, (h, c)
        elif mode == "GRU":
            def step(x, h, wi, wh, bi, bh):
                gi = x @ wi.T + bi
                gh = h @ wh.T + bh
                ir, iz, ic = jnp.split(gi, 3, axis=-1)
                hr, hz, hc = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(ir + hr)
                z = jax.nn.sigmoid(iz + hz)
                c = jnp.tanh(ic + r * hc)
                h = (1 - z) * c + z * h
                return h, h
        else:
            act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu

            def step(x, h, wi, wh, bi, bh):
                h = act(x @ wi.T + bi + h @ wh.T + bh)
                return h, h
        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        inputs = ensure_tensor(inputs)
        mode = self.MODE
        is_lstm = mode == "LSTM"
        nd, nl, H = self.num_directions, self.num_layers, self.hidden_size
        time_major = self.time_major
        B = inputs.shape[0 if time_major else 1]

        from paddle_tpu.ops.creation import zeros
        if initial_states is None:
            if is_lstm:
                initial_states = (zeros([nl * nd, B, H], inputs.dtype),
                                  zeros([nl * nd, B, H], inputs.dtype))
            else:
                initial_states = zeros([nl * nd, B, H], inputs.dtype)
        step_fn = self._cell_step(mode)
        params = (self.weight_ih_list + self.weight_hh_list + self.bias_ih_list +
                  self.bias_hh_list)
        n = nl * nd
        state_ts = list(initial_states) if is_lstm else [initial_states]

        def prim(x, *arrs):
            states = arrs[:len(state_ts)]
            ws = arrs[len(state_ts):]
            wi_l = ws[:n]
            wh_l = ws[n:2 * n]
            bi_l = ws[2 * n:3 * n]
            bh_l = ws[3 * n:4 * n]
            seq = x if time_major else jnp.swapaxes(x, 0, 1)  # [T, B, *]
            out = seq
            final_h = []
            final_c = []
            for layer in range(nl):
                dir_outs = []
                for d in range(nd):
                    idx = layer * nd + d
                    h0 = states[0][idx]
                    state0 = (h0, states[1][idx]) if is_lstm else h0
                    src = out if d == 0 else jnp.flip(out, axis=0)

                    def scan_fn(carry, xt, _wi=wi_l[idx], _wh=wh_l[idx],
                                _bi=bi_l[idx], _bh=bh_l[idx]):
                        y, new_carry = step_fn(xt, carry, _wi, _wh, _bi, _bh)
                        return new_carry, y

                    carry, ys = jax.lax.scan(scan_fn, state0, src)
                    if d == 1:
                        ys = jnp.flip(ys, axis=0)
                    dir_outs.append(ys)
                    if is_lstm:
                        final_h.append(carry[0])
                        final_c.append(carry[1])
                    else:
                        final_h.append(carry)
                out = dir_outs[0] if nd == 1 else jnp.concatenate(dir_outs, -1)
            y = out if time_major else jnp.swapaxes(out, 0, 1)
            if is_lstm:
                return y, jnp.stack(final_h), jnp.stack(final_c)
            return y, jnp.stack(final_h)

        res = apply(prim, inputs, *state_ts, *params, op_name=mode.lower())
        if is_lstm:
            y, h, c = res
            return y, (h, c)
        y, h = res
        return y, h


class SimpleRNN(_RNNBase):
    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        self.MODE = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"
