"""Activation layers (ref: `python/paddle/nn/layer/activation.py`)."""
from __future__ import annotations

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.framework.param_attr import ParamAttr


def _simple(fname, cls_name, **fixed):
    def forward(self, x):
        return getattr(F, fname)(x, **fixed, **self._kwargs)

    def __init__(self, *args, name=None, **kwargs):
        Layer.__init__(self)
        self._kwargs = kwargs
        for a, k in zip(args, _ARG_NAMES.get(cls_name, [])):
            self._kwargs[k] = a

    return type(cls_name, (Layer,), {"__init__": __init__, "forward": forward})


_ARG_NAMES = {
    "LeakyReLU": ["negative_slope"],
    "ELU": ["alpha"],
    "CELU": ["alpha"],
    "GELU": ["approximate"],
    "Hardshrink": ["threshold"],
    "Softshrink": ["threshold"],
    "Hardtanh": ["min", "max"],
    "Softplus": ["beta", "threshold"],
    "ThresholdedReLU": ["threshold", "value"],
    "Softmax": ["axis"],
    "LogSoftmax": ["axis"],
    "Maxout": ["groups", "axis"],
    "GLU": ["axis"],
}

ReLU = _simple("relu", "ReLU")
ReLU6 = _simple("relu6", "ReLU6")
Sigmoid = _simple("sigmoid", "Sigmoid")
Tanh = _simple("tanh", "Tanh")
LeakyReLU = _simple("leaky_relu", "LeakyReLU")
ELU = _simple("elu", "ELU")
CELU = _simple("celu", "CELU")
SELU = _simple("selu", "SELU")
GELU = _simple("gelu", "GELU")
Hardshrink = _simple("hardshrink", "Hardshrink")
Hardsigmoid = _simple("hardsigmoid", "Hardsigmoid")
Hardswish = _simple("hardswish", "Hardswish")
Hardtanh = _simple("hardtanh", "Hardtanh")
Mish = _simple("mish", "Mish")
Silu = _simple("silu", "Silu")
Swish = _simple("swish", "Swish")
Softplus = _simple("softplus", "Softplus")
Softshrink = _simple("softshrink", "Softshrink")
Softsign = _simple("softsign", "Softsign")
Tanhshrink = _simple("tanhshrink", "Tanhshrink")
ThresholdedReLU = _simple("thresholded_relu", "ThresholdedReLU")
LogSigmoid = _simple("log_sigmoid", "LogSigmoid")
Softmax = _simple("softmax", "Softmax")
LogSoftmax = _simple("log_softmax", "LogSoftmax")
Maxout = _simple("maxout", "Maxout")
GLU = _simple("glu", "GLU")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        attr = ParamAttr._to_attr(weight_attr)
        if attr is None:
            attr = ParamAttr(initializer=I.Constant(init))
        elif isinstance(attr, ParamAttr) and attr.initializer is None:
            attr.initializer = I.Constant(init)
        self._weight = self.create_parameter((num_parameters,), attr=attr)
        self._data_format = data_format

    @property
    def weight(self):
        return self._weight

    def forward(self, x):
        return F.prelu(x, self._weight, data_format=self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)
