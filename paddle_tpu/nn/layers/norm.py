"""Norm layers (ref: `python/paddle/nn/layer/norm.py`)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.framework.param_attr import ParamAttr


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None,
                 name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            attr = ParamAttr._to_attr(weight_attr)
            if attr is None:
                attr = ParamAttr(initializer=I.Constant(1.0))
            elif isinstance(attr, ParamAttr) and attr.initializer is None:
                attr.initializer = I.Constant(1.0)
            self.weight = self.create_parameter((num_features,), attr=attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_features,), attr=ParamAttr._to_attr(bias_attr), is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, jnp.float32),
                                             _internal=True))
        self.register_buffer("_variance",
                             Tensor(jnp.ones(num_features, jnp.float32),
                                    _internal=True))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """Legacy fluid-style BatchNorm (ref `python/paddle/nn/layer/norm.py` BatchNorm):
    acts like eval-mode unless .train() — kept for API parity."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout, use_global_stats)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None,
                 name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under pjit/GSPMD batch stats are computed over the global
    batch automatically (XLA inserts the cross-replica reductions), so this equals
    BatchNorm on TPU (ref: `python/paddle/nn/layer/norm.py` SyncBatchNorm over
    `c_sync_calc_stream` + custom CUDA kernels).
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        if isinstance(layer, _BatchNormBase) and not isinstance(layer,
                                                                SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon, data_format=layer._data_format)
            if layer.weight is not None:
                out.weight = layer.weight
            if layer.bias is not None:
                out.bias = layer.bias
            out._mean = layer._mean
            out._variance = layer._variance
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            attr = ParamAttr._to_attr(weight_attr)
            if attr is None:
                attr = ParamAttr(initializer=I.Constant(1.0))
            elif isinstance(attr, ParamAttr) and attr.initializer is None:
                attr.initializer = I.Constant(1.0)
            self.weight = self.create_parameter(self._normalized_shape, attr=attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=ParamAttr._to_attr(bias_attr),
                is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
        else:
            attr = ParamAttr._to_attr(weight_attr)
            if attr is None:
                attr = ParamAttr(initializer=I.Constant(1.0))
            self.weight = self.create_parameter((num_channels,), attr=attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_channels,), attr=ParamAttr._to_attr(bias_attr), is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.scale = None
        else:
            attr = ParamAttr._to_attr(weight_attr)
            if attr is None:
                attr = ParamAttr(initializer=I.Constant(1.0))
            self.scale = self.create_parameter((num_features,), attr=attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_features,), attr=ParamAttr._to_attr(bias_attr), is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k,
                                     self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter(
            (h,), attr=ParamAttr(initializer=I.Normal(0, 1), trainable=False))
        self.weight_v = self.create_parameter(
            (w,), attr=ParamAttr(initializer=I.Normal(0, 1), trainable=False))

    def forward(self, x):
        return F.spectral_norm(x, self.weight_u, self.weight_v, self._dim,
                               self._power_iters, self._epsilon)
