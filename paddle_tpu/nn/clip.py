"""Gradient clipping (ref: `python/paddle/fluid/clip.py` — ClipGradByGlobalNorm at
:422; the hybrid-parallel-aware variant lives in distributed.fleet)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max),
                                  _internal=True)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(g._data.astype(jnp.float32) ** 2))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._data * scale).astype(g.dtype),
                                  _internal=True)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _global_norm(self, grads, params=None):
        sq = []
        for i, g in enumerate(grads):
            s = jnp.sum(g._data.astype(jnp.float32) ** 2)
            p = params[i] if params is not None else None
            # packed pipeline params with cross-stage TIED slots carry the
            # SUMMED grad in every copy (so updates stay identical); the
            # duplicates must not re-count in the global norm or clipping
            # diverges from the serial model (which holds the param once)
            for row, off, n in getattr(p, "_tied_dup_slots", ()):
                dup = g._data[row, off:off + n].astype(jnp.float32)
                s = s - jnp.sum(dup * dup)
            sq.append(s)
        return jnp.sqrt(sum(sq))

    def _dygraph_clip(self, params_grads):
        # params with need_clip=False stay out of the norm sum too (ref
        # _dygraph_clip filters before computing the norm)
        pairs = [(p, g) for p, g in params_grads
                 if g is not None and getattr(p, "need_clip", True)]
        if not pairs:
            return params_grads
        global_norm = self._global_norm([g for _, g in pairs],
                                        params=[p for p, _ in pairs])
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or getattr(p, "need_clip", True) is False:
                out.append((p, g))
            else:
                out.append((p, Tensor((g._data * scale).astype(g.dtype),
                                      _internal=True)))
        return out


GradientClipBase = ClipGradBase
GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()), _internal=True)
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._data.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._write((p.grad._data * scale).astype(p.grad.dtype))
    return Tensor(total, _internal=True)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._write(jnp.clip(p.grad._data, -clip_value, clip_value))
