"""nn.Layer — the module system.

Ref: `python/paddle/fluid/dygraph/layers.py:108` (Layer with parameters/buffers/
sublayers, forward hooks, state_dict, train/eval). Parameters are mutable Tensors
(rebinding immutable jax arrays), which is what lets the same Layer object run both
eagerly and inside a captured/jitted train step.
"""
from __future__ import annotations

import collections
import itertools
from typing import Callable, Iterator

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, Parameter

_param_name_counter = itertools.count()
from paddle_tpu.core import dtype as dtype_mod


class HookRemoveHelper:
    def __init__(self, hooks: dict, hook_id: int):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype=None):
        # dtype None = the GLOBAL default (paddle.set_default_dtype), resolved
        # at create_parameter time (ref layers.py Layer: uses
        # paddle.get_default_dtype() unless the layer pins one)
        self.training = True
        self._dtype = dtype
        self._parameters: dict[str, Parameter] = collections.OrderedDict()
        self._buffers: dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers: dict[str, "Layer"] = collections.OrderedDict()
        self._forward_pre_hooks: dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: dict[int, Callable] = collections.OrderedDict()
        self._hook_counter = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ------------------------------------------------------------- registration

    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
            self.__dict__.pop(name, None)
        elif buffers is not None and name in buffers:
            if value is None:
                buffers[name] = None
            elif isinstance(value, Tensor):
                buffers[name] = value
            else:
                buffers[name].set_value(value)
        else:
            if params is not None and name in params and value is None:
                params.pop(name)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter requires a Parameter")
        self._parameters[str(name)] = parameter
        return parameter

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None:
            tensor.persistable = True
        self._buffers[str(name)] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(str(name))
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """ref: `layers.py` create_parameter — honors ParamAttr initializer."""
        from paddle_tpu.nn import initializer as I
        from paddle_tpu.framework.param_attr import ParamAttr
        dtype = dtype_mod.convert_dtype(dtype or self._dtype)
        init = None
        trainable = True
        if isinstance(attr, ParamAttr):
            init = attr.initializer
            trainable = attr.trainable
        elif callable(attr):
            init = attr
        if init is None:
            init = default_initializer or (
                I.Constant(0.0) if is_bias else I.XavierUniform())
        data = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(data, trainable=trainable)
        if isinstance(attr, ParamAttr):
            p.need_clip = attr.need_clip
            if attr.learning_rate != 1.0:
                p.optimize_attr = {"learning_rate": attr.learning_rate}
        if isinstance(attr, ParamAttr) and attr.name:
            p.name = attr.name
        else:
            # unique auto-name (ref framework.py unique_name): optimizer/ckpt
            # state is keyed by param name, so every param needs one
            kind = "b" if is_bias else "w"
            p.name = f"{self._name_scope}_{next(_param_name_counter)}.{kind}_0"
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None):
        return Tensor(jnp.zeros((), dtype_mod.convert_dtype(dtype or self._dtype)),
                      _internal=True)

    # ------------------------------------------------------------- iteration

    def parameters(self, include_sublayers=True) -> list:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[tuple[str, Parameter]]:
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p

    def buffers(self, include_sublayers=True) -> list:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def _traverse(self, prefix="", include_sublayers=True):
        yield prefix, self
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{name}" if prefix else name
                yield from sub._traverse(sub_prefix, True)

    def children(self) -> Iterator["Layer"]:
        for _, sub in self.named_children():
            yield sub

    def named_children(self):
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def sublayers(self, include_self=False) -> list:
        out = []
        for _, layer in self._traverse("", True):
            out.append(layer)
        return out if include_self else out[1:]

    def named_sublayers(self, prefix="", include_self=False):
        for name, layer in self._traverse(prefix, True):
            if not include_self and layer is self:
                continue
            yield name, layer

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def full_name(self):
        return self._name_scope

    # ------------------------------------------------------------- modes

    def train(self):
        self.training = True
        for sub in self.sublayers():
            sub.training = True
        return self

    def eval(self):
        self.training = False
        for sub in self.sublayers():
            sub.training = False
        return self

    # ------------------------------------------------------------- hooks

    def register_forward_pre_hook(self, hook):
        self._hook_counter += 1
        self._forward_pre_hooks[self._hook_counter] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_counter)

    def register_forward_post_hook(self, hook):
        self._hook_counter += 1
        self._forward_post_hooks[self._hook_counter] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_counter)

    # ------------------------------------------------------------- call

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in tuple(self._forward_pre_hooks.values()):
            res = hook(self, inputs)
            if res is not None:
                inputs = res if isinstance(res, tuple) else (res,)
        out = self.forward(*inputs, **kwargs)
        for hook in tuple(self._forward_post_hooks.values()):
            res = hook(self, inputs, out)
            if res is not None:
                out = res
        return out

    # ------------------------------------------------------------- state

    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[structured_name_prefix + name] = p
        for name, layer in self._traverse("", include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                full = f"{name}.{bname}" if name else bname
                dest[structured_name_prefix + full] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Load values into existing parameters/buffers (shape-checked)."""
        own = self.state_dict()
        missing, unexpected = [], []
        for key, target in own.items():
            if key in state_dict:
                v = state_dict[key]
                arr = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                if tuple(arr.shape) != tuple(target._data.shape):
                    raise ValueError(
                        f"shape mismatch for {key}: checkpoint {tuple(arr.shape)} vs "
                        f"parameter {tuple(target._data.shape)}")
                target._write(arr.astype(target.dtype))
            else:
                missing.append(key)
        for key in state_dict:
            if key not in own:
                unexpected.append(key)
        return missing, unexpected

    load_dict = set_state_dict

    # ------------------------------------------------------------- dtype/device

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = dtype_mod.convert_dtype(dtype)
            for p in self.parameters():
                p._write(p._data.astype(d))
            for b in self.buffers():
                if jnp.issubdtype(b.dtype, jnp.floating):
                    b._write(b._data.astype(d))
            self._dtype = d
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            body = repr(sub).split("\n")
            body = [body[0]] + ["  " + ln for ln in body[1:]]
            lines.append(f"({name}): " + "\n".join(body))
        main = self.__class__.__name__ + "(" + extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    def extra_repr(self):
        return ""
