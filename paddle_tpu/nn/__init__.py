"""paddle.nn surface (ref: `python/paddle/nn/__init__.py`)."""
from paddle_tpu.nn.layer import Layer  # noqa: F401
from paddle_tpu.nn import functional  # noqa: F401
from paddle_tpu.nn import initializer  # noqa: F401
from paddle_tpu.nn.layers.container import (  # noqa: F401
    Sequential, LayerList, ParameterList, LayerDict,
)
from paddle_tpu.nn.layers.common import (  # noqa: F401
    Linear, Identity, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout,
    Flatten, Unflatten, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D,
    Bilinear, CosineSimilarity, PairwiseDistance, Pad1D, Pad2D, Pad3D, ZeroPad2D,
    PixelShuffle, PixelUnshuffle, ChannelShuffle,
)
from paddle_tpu.nn.layers.conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
)
from paddle_tpu.nn.layers.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm, LayerNorm,
    GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D, LocalResponseNorm,
    SpectralNorm,
)
from paddle_tpu.nn.layers.pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D, AdaptiveMaxPool1D,
    AdaptiveMaxPool2D, AdaptiveMaxPool3D,
)
from paddle_tpu.nn.layers.activation import (  # noqa: F401
    ReLU, ReLU6, Sigmoid, Tanh, LeakyReLU, ELU, CELU, SELU, GELU, Hardshrink,
    Hardsigmoid, Hardswish, Hardtanh, Mish, Silu, Swish, Softplus, Softshrink,
    Softsign, Tanhshrink, ThresholdedReLU, LogSigmoid, Softmax, LogSoftmax,
    Maxout, GLU, PReLU, RReLU,
)
from paddle_tpu.nn.layers.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    KLDivLoss, SmoothL1Loss, MarginRankingLoss, HingeEmbeddingLoss,
    CosineEmbeddingLoss, TripletMarginLoss, CTCLoss,
)
from paddle_tpu.nn.layers.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from paddle_tpu.nn.layers.rnn import (  # noqa: F401
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN, LSTM,
    GRU,
)
from paddle_tpu.nn import utils  # noqa: F401
from paddle_tpu.nn.clip import ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm  # noqa: F401
