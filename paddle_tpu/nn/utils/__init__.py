"""nn.utils (ref: `python/paddle/nn/utils/` — weight_norm, spectral_norm helpers,
parameter vector utils)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, Parameter
from paddle_tpu.nn.layer import Layer


def parameters_to_vector(parameters, name=None):
    arrs = [p._data.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(arrs), _internal=True)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = int(np.prod(p._data.shape))
        p._write(vec._data[offset:offset + n].reshape(p._data.shape)
                 .astype(p.dtype))
        offset += n


def _norm_except_dim(w, dim):
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True))


class _WeightNorm:
    """Reparameterize weight = g * v / ||v|| via a forward-pre-hook
    (ref: `python/paddle/nn/utils/weight_norm_hook.py`)."""

    def __init__(self, name, dim):
        self.name = name
        self.dim = dim if dim is not None else -1

    def compute_weight(self, layer):
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        from paddle_tpu.core.autograd import apply
        dim = self.dim

        def prim(gg, vv):
            if dim == -1:
                norm = jnp.sqrt(jnp.sum(vv * vv))
            else:
                axes = tuple(i for i in range(vv.ndim) if i != dim)
                norm = jnp.sqrt(jnp.sum(vv * vv, axis=axes, keepdims=True))
            return vv * (gg / norm)

        return apply(prim, g, v, op_name="weight_norm")

    def __call__(self, layer, inputs):
        w = self.compute_weight(layer)
        object.__setattr__(layer, "_weight_norm_computed", w)
        layer._parameters.pop(self.name, None)
        layer.__dict__[self.name] = w


def weight_norm(layer: Layer, name="weight", dim=0):
    w = layer._parameters[name]
    fn = _WeightNorm(name, dim)
    dimv = dim if dim is not None else -1
    if dimv == -1:
        norm = jnp.sqrt(jnp.sum(w._data * w._data))
    else:
        norm = _norm_except_dim(w._data, dimv)
    g = Parameter(jnp.asarray(norm), trainable=True)
    v = Parameter(w._data, trainable=True)
    del layer._parameters[name]
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    handle = layer.register_forward_pre_hook(fn)
    layer._weight_norm_hook = (fn, handle)
    return layer


def remove_weight_norm(layer: Layer, name="weight"):
    fn, handle = layer._weight_norm_hook
    w = fn.compute_weight(layer)
    handle.remove()
    layer.__dict__.pop(name, None)
    layer._parameters.pop(name + "_g", None)
    layer._parameters.pop(name + "_v", None)
    layer.add_parameter(name, Parameter(w._data, trainable=True))
    return layer


def spectral_norm(layer: Layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    from paddle_tpu.nn.layers.norm import SpectralNorm as _SN
    w = layer._parameters[name]
    if dim is None:
        dim = 0
    sn = _SN(tuple(w._data.shape), dim=dim, power_iters=n_power_iterations,
             epsilon=eps)

    def hook(l, inputs):
        normed = sn(getattr(l, name + "_orig"))
        l._parameters.pop(name, None)
        l.__dict__[name] = normed

    orig = Parameter(w._data, trainable=True)
    del layer._parameters[name]
    layer.add_parameter(name + "_orig", orig)
    layer.add_sublayer(name + "_sn", sn)
    layer.register_forward_pre_hook(hook)
    return layer
