"""paddle.nn.functional surface (ref: `python/paddle/nn/functional/__init__.py`)."""
from paddle_tpu.nn.functional.activation import *  # noqa: F401,F403
from paddle_tpu.nn.functional.common import *  # noqa: F401,F403
from paddle_tpu.nn.functional.conv import (  # noqa: F401
    conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose, conv3d_transpose,
)
from paddle_tpu.nn.functional.pooling import *  # noqa: F401,F403
from paddle_tpu.nn.functional.norm import (  # noqa: F401
    batch_norm, layer_norm, instance_norm, group_norm, local_response_norm,
    spectral_norm,
)
from paddle_tpu.nn.functional.loss import *  # noqa: F401,F403
from paddle_tpu.nn.functional.attention import (  # noqa: F401
    scaled_dot_product_attention, sequence_mask,
    sequence_parallel_attention,
)
from paddle_tpu.nn.functional.vision import affine_grid, grid_sample  # noqa: F401
from paddle_tpu.nn.functional.extension import gather_tree, temporal_shift  # noqa: F401
from paddle_tpu.ops.random import gumbel_softmax  # noqa: F401
