"""Extension functionals (ref: `python/paddle/nn/functional/extension.py` —
gather_tree, temporal_shift; C++ kernels `paddle/phi/kernels/gather_tree_kernel.h`,
`temporal_shift_kernel.h`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply
from paddle_tpu.ops.common import ensure_tensor

__all__ = ["gather_tree", "temporal_shift"]


def gather_tree(ids, parents, name=None):
    """Backtrace full beam-search predictions from per-step ids and parent
    indices (paddle.nn.functional.gather_tree). ids/parents: [max_time, batch,
    beam]. The reference kernel walks time backwards; here that walk is a
    ``lax.scan`` in reversed time so it stays jittable."""
    ids, parents = ensure_tensor(ids), ensure_tensor(parents)

    def fn(i, p):
        t, b, k = i.shape
        batch_idx = jnp.arange(b)[:, None]

        def step(beam, inputs):
            idt, part = inputs          # [b, k] each, at time t
            out = idt[batch_idx, beam]  # gather along beam
            nxt = part[batch_idx, beam]
            return nxt, out

        init = jnp.broadcast_to(jnp.arange(k, dtype=p.dtype)[None, :], (b, k))
        # walk from the last step to the first
        _, outs = jax.lax.scan(step, init, (i[::-1], p[::-1]))
        return outs[::-1]

    return apply(fn, ids, parents, op_name="gather_tree")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    """Temporal Shift Module (paddle.nn.functional.temporal_shift; ref
    extension.py / `temporal_shift_op.cc`): shift a leading fraction of channels
    one step back in time, the next fraction one step forward."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"data_format should be NCHW or NHWC, got {data_format}")
    x = ensure_tensor(x)
    seg = int(seg_num)

    def fn(a):
        if data_format == "NHWC":
            a = a.transpose(0, 3, 1, 2)
        nt, c, h, w = a.shape
        n = nt // seg
        a = a.reshape(n, seg, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        pad = jnp.pad(a, ((0, 0), (1, 1), (0, 0), (0, 0), (0, 0)))
        back = pad[:, 2:, :c1]                # out[t] = x[t+1]: shift back in time
        fwd = pad[:, :seg, c1:c2]             # out[t] = x[t-1]: shift forward
        keep = a[:, :, c2:]
        out = jnp.concatenate([back, fwd, keep], axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = out.transpose(0, 2, 3, 1)
        return out

    return apply(fn, x, op_name="temporal_shift")
