"""Convolution functionals (ref: `python/paddle/nn/functional/conv.py`; cuDNN kernels
`phi/kernels/gpudnn/conv_kernel.cu` -> here a single `lax.conv_general_dilated`,
which XLA maps onto the MXU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.common import ensure_tensor
from paddle_tpu.amp.state import amp_cast_inputs


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    # nested [[lo, hi], ...]
    return [tuple(int(q) for q in p) for p in padding]


def _conv(x, weight, bias, stride, padding, dilation, groups, n_spatial,
          data_format, op_name):
    x, weight = amp_cast_inputs(op_name, ensure_tensor(x), ensure_tensor(weight))
    strides = _tuple(stride, n_spatial)
    dilations = _tuple(dilation, n_spatial)
    pads = _padding(padding, n_spatial)
    channels_last = data_format.endswith("C")
    spatial = "DHW"[-n_spatial:] if n_spatial > 1 else "W"
    if channels_last:
        lhs_spec = "N" + spatial + "C"
    else:
        lhs_spec = "NC" + spatial
    rhs_spec = "OI" + spatial
    out_spec = lhs_spec
    dn = jax.lax.conv_dimension_numbers(
        tuple(x.shape), tuple(weight.shape), (lhs_spec, rhs_spec, out_spec))

    def prim(a, w):
        return jax.lax.conv_general_dilated(
            a, w, strides, pads, rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=a.dtype if a.dtype != jnp.float64 else None)

    out = apply(prim, x, weight, op_name=op_name)
    if bias is not None:
        bias = ensure_tensor(bias)
        if bias.dtype != out.dtype:
            bias = bias.astype(out.dtype)
        shape = [1] * (n_spatial + 2)
        shape[-1 if channels_last else 1] = bias.shape[0]
        out = out + bias.reshape(shape)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, fmt,
                 "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n_spatial, data_format, op_name, output_size=None):
    x, weight = amp_cast_inputs(op_name, ensure_tensor(x), ensure_tensor(weight))
    strides = _tuple(stride, n_spatial)
    dilations = _tuple(dilation, n_spatial)
    out_pads = _tuple(output_padding, n_spatial)
    channels_last = data_format.endswith("C")
    spatial = "DHW"[-n_spatial:] if n_spatial > 1 else "W"
    lhs_spec = ("N" + spatial + "C") if channels_last else ("NC" + spatial)
    # paddle transpose-conv weights are [in, out/groups, *k] = IOHW
    rhs_spec = "IO" + spatial
    dn = jax.lax.conv_dimension_numbers(
        tuple(x.shape), tuple(weight.shape), (lhs_spec, rhs_spec, lhs_spec))

    if isinstance(padding, str):
        pads = padding.upper()
    else:
        pads = _padding(padding, n_spatial)

    # Computed as grad-of-conv: dilate the input by the stride, flip the kernel.
    # output_padding extends the high side, matching the reference semantics.
    def prim2(a, w):
        if isinstance(pads, str):
            out = jax.lax.conv_transpose(
                a, w, strides, padding=pads, rhs_dilation=dilations,
                dimension_numbers=dn, transpose_kernel=True,
                feature_group_count=groups)
            return out
        # compute as grad-of-conv: dilate input by stride, flip kernel
        pad_cfg = []
        for i, (lo, hi) in enumerate(pads):
            k = (w.shape[2 + i] - 1) * dilations[i] + 1
            pad_cfg.append((k - 1 - lo, k - 1 - hi + out_pads[i]))
        w_flipped = jnp.flip(w, axis=tuple(range(2, w.ndim)))
        # IOHW -> OIHW with groups: [I, O/g, *k] -> [O, I/g, *k]
        i_dim, og = w.shape[0], w.shape[1]
        wf = w_flipped.reshape((groups, i_dim // groups) + w.shape[1:])
        wf = jnp.moveaxis(wf, 2, 1)  # [g, O/g, I/g, *k]
        wf = wf.reshape((og * groups, i_dim // groups) + w.shape[2:])
        dn2 = jax.lax.conv_dimension_numbers(
            tuple(a.shape), tuple(wf.shape), (lhs_spec, "OI" + spatial, lhs_spec))
        return jax.lax.conv_general_dilated(
            a, wf, window_strides=(1,) * n_spatial, padding=pad_cfg,
            lhs_dilation=strides, rhs_dilation=dilations,
            dimension_numbers=dn2, feature_group_count=groups)

    out = apply(prim2, x, weight, op_name=op_name)
    if output_size is not None:
        want = [int(s) for s in (output_size if isinstance(output_size, (list, tuple))
                                 else [output_size] * n_spatial)]
        have = out.shape[2:] if not channels_last else out.shape[1:-1]
        if list(have) != want:
            extra = [w0 - h for w0, h in zip(want, have)]
            widths = [(0, 0), (0, 0)] + [(0, e) for e in extra] if not channels_last \
                else [(0, 0)] + [(0, e) for e in extra] + [(0, 0)]
            out = apply(lambda a: jnp.pad(a, widths), out, op_name="output_size_pad")
    if bias is not None:
        bias = ensure_tensor(bias)
        if bias.dtype != out.dtype:
            bias = bias.astype(out.dtype)
        shape = [1] * (n_spatial + 2)
        shape[-1 if channels_last else 1] = bias.shape[0]
        out = out + bias.reshape(shape)
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL",
                     name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, fmt, "conv1d_transpose",
                           output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW",
                     name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format, "conv2d_transpose",
                           output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW",
                     name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format, "conv3d_transpose",
                           output_size)
