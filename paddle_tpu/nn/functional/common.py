"""Common functionals: linear, dropout, embedding, pad, interpolate, one-hot, etc.
(ref: `python/paddle/nn/functional/common.py` — `linear` at :1822 dispatches to
`_C_ops.linear`; here it is one fused XLA dot+bias).
"""
from __future__ import annotations

import builtins
import math

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.common import ensure_tensor
from paddle_tpu.amp.state import amp_cast_inputs


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b, W shaped [in, out] (paddle convention)."""
    x, weight = amp_cast_inputs("linear", ensure_tensor(x), ensure_tensor(weight))
    if bias is not None:
        bias = ensure_tensor(bias)
        if bias.dtype != x.dtype:
            bias = bias.astype(x.dtype)
        return apply(lambda a, w, b: jnp.matmul(a, w) + b, x, weight, bias,
                     op_name="linear")
    return apply(jnp.matmul, x, weight, op_name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply(lambda a: a * (1 - p), x, op_name="dropout_infer")
        return x
    if p == 1.0:
        return apply(lambda a: jnp.zeros_like(a), x, op_name="dropout")
    from paddle_tpu.ops.random import default_generator
    key = default_generator().next_key()
    ax = None if axis is None else tuple(axis) if isinstance(axis, (list, tuple)) \
        else (axis,)

    def prim(a):
        shape = a.shape if ax is None else tuple(
            a.shape[i] if i in ax else 1 for i in range(a.ndim))
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return apply(prim, x, op_name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p=p, axis=list(ax), training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p=p, axis=list(ax), training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        return x
    from paddle_tpu.ops.random import default_generator
    key = default_generator().next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def prim(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        A = (1.0 / math.sqrt((1 - p) * (1 + p * alpha_p ** 2)))
        B = -A * alpha_p * p
        return (A * jnp.where(keep, a, alpha_p) + B).astype(a.dtype)

    return apply(prim, x, op_name="alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Lookup rows of ``weight`` (ref `phi/kernels/embedding_kernel.h`; the
    vocab-parallel variant lives in distributed.fleet).

    ``sparse=True`` reproduces the reference's SelectedRows gradient path
    (`embedding_sparse_grad_kernel.h`): ``weight.grad`` becomes a
    :class:`~paddle_tpu.core.selected_rows.SelectedRows` holding only the
    looked-up rows, and the optimizers apply a row-wise scatter update.
    Eager-mode feature (the captured/jit path keeps dense grads, where XLA's
    scatter fusion already gives the same effect)."""
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    if padding_idx is not None and padding_idx < 0:
        # paddle normalizes negative padding_idx against the vocab size
        padding_idx = weight.shape[0] + padding_idx
    from paddle_tpu.core import tensor as tensor_mod
    if (sparse and not tensor_mod.in_capture()
            and weight._grad_node is None):
        # leaf weights only: for a computed weight (weight-norm/LoRA style)
        # the SelectedRows would land on the intermediate and the real
        # parameters would get nothing — use the dense path there
        return _sparse_embedding(x, weight, padding_idx)

    def prim(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out).astype(w.dtype)
        return out

    return apply(prim, x, weight, op_name="embedding")


class _SparseEmbedding:
    """Module-level PyLayer (built lazily to avoid an import cycle) whose
    backward delivers the weight grad out-of-band as SelectedRows."""
    _cls = None

    @classmethod
    def get(cls):
        if cls._cls is None:
            from paddle_tpu.autograd import PyLayer
            from paddle_tpu.core.selected_rows import SelectedRows

            class Impl(PyLayer):
                @staticmethod
                def forward(ctx, ids, w, padding_idx=None):
                    ctx.ids = ids._data
                    ctx.w = w
                    ctx.padding_idx = padding_idx
                    out = jnp.take(w._data, ids._data, axis=0)
                    if padding_idx is not None:
                        mask = (ids._data == padding_idx)[..., None]
                        out = jnp.where(mask, 0.0, out).astype(w.dtype)
                    return Tensor(out, _internal=True)

                @staticmethod
                def backward(ctx, d_out):
                    ids = ctx.ids.reshape(-1)
                    vals = d_out._data.reshape(-1, d_out.shape[-1])
                    if ctx.padding_idx is not None:
                        vals = jnp.where((ids == ctx.padding_idx)[:, None],
                                         0.0, vals).astype(vals.dtype)
                    sr = SelectedRows(ids, vals, ctx.w.shape[0])
                    prev = ctx.w._grad
                    if isinstance(prev, SelectedRows):
                        ctx.w._grad = prev.accumulate(sr)
                    elif prev is not None:
                        # a dense grad already landed (e.g. tied lm-head
                        # weights): densify so neither contribution is lost
                        ctx.w._grad = Tensor(
                            prev._data + sr.to_dense().astype(prev.dtype),
                            _internal=True)
                    else:
                        ctx.w._grad = sr
                    # weight grad delivered out-of-band; ids carry none
                    return None, None

            cls._cls = Impl
        return cls._cls


def _sparse_embedding(x, weight, padding_idx):
    return _SparseEmbedding.get().apply(x, weight, padding_idx=padding_idx)


def one_hot(x, num_classes, name=None):
    from paddle_tpu.ops.manipulation import one_hot as _oh
    return _oh(x, num_classes)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = ensure_tensor(label)
    if prior_dist is not None:
        pd = ensure_tensor(prior_dist)
        return apply(lambda l, p: (1 - epsilon) * l + epsilon * p, label, pd,
                     op_name="label_smooth")
    return apply(lambda l: (1 - epsilon) * l + epsilon / l.shape[-1], label,
                 op_name="label_smooth")


_PAD_MODE = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]

    def prim(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            # full-rank pad, paddle flat format [before0, after0, before1, ...]
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # partial spatial pad on the last dims per data_format, reversed pairs
            n_spatial = len(pad) // 2
            widths = [(0, 0)] * nd
            channels_last = data_format.endswith("C")
            for i in range(n_spatial):
                lo, hi = pad[2 * i], pad[2 * i + 1]
                if channels_last:
                    dim = nd - 2 - i
                else:
                    dim = nd - 1 - i
                widths[dim] = (lo, hi)
        if mode == "constant":
            return jnp.pad(a, widths, constant_values=value)
        return jnp.pad(a, widths, mode=_PAD_MODE[mode])

    return apply(prim, x, op_name="pad")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (ref `phi/kernels/funcs/im2col.cu:87`). Output [N, C*kh*kw, L]."""
    x = ensure_tensor(x)

    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    dh, dw = pair(dilations)
    p = paddings
    if isinstance(p, int):
        pt = pb = pl = pr = p
    elif len(p) == 2:
        pt, pb, pl, pr = p[0], p[0], p[1], p[1]
    else:
        pt, pl, pb, pr = p

    def prim(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
        oh = (a.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
        ow = (a.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            a, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return patches.reshape(n, c * kh * kw, oh * ow)

    return apply(prim, x, op_name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    x = ensure_tensor(x)

    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    oh, ow = pair(output_sizes)
    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    dh, dw = pair(dilations)
    p = paddings
    if isinstance(p, int):
        pt = pb = pl = pr = p
    elif len(p) == 2:
        pt, pb, pl, pr = p[0], p[0], p[1], p[1]
    else:
        pt, pl, pb, pr = p

    def prim(a):
        n, ckk, L = a.shape
        c = ckk // (kh * kw)
        hh, ww = oh + pt + pb, ow + pl + pr
        lh = (hh - (dh * (kh - 1) + 1)) // sh + 1
        lw = (ww - (dw * (kw - 1) + 1)) // sw + 1
        a = a.reshape(n, c, kh, kw, lh, lw)
        out = jnp.zeros((n, c, hh, ww), a.dtype)
        for i in range(kh):
            for j in range(kw):
                patch = a[:, :, i, j]
                out = out.at[:, :,
                             i * dh: i * dh + lh * sh: sh,
                             j * dw: j * dw + lw * sw: sw].add(patch)
        return out[:, :, pt: pt + oh, pl: pl + ow]

    return apply(prim, x, op_name="fold")


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    """ref: `python/paddle/nn/functional/common.py` interpolate -> jax.image."""
    x = ensure_tensor(x)
    channels_last = data_format.endswith("C")
    n_spatial = x.ndim - 2

    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        out_spatial = [int(s._data) if isinstance(s, Tensor) else int(s)
                       for s in (size if isinstance(size, (list, tuple)) else [size])]
    else:
        if isinstance(scale_factor, (int, float)):
            scales = [scale_factor] * n_spatial
        else:
            scales = list(scale_factor)
        spatial = x.shape[2:] if not channels_last else x.shape[1:-1]
        out_spatial = [int(s * f) for s, f in zip(spatial, scales)]

    jmode = {"nearest": "nearest", "bilinear": "bilinear", "trilinear": "trilinear",
             "bicubic": "bicubic", "linear": "linear", "area": "linear"}[mode]

    def prim(a):
        if channels_last:
            out_shape = (a.shape[0],) + tuple(out_spatial) + (a.shape[-1],)
        else:
            out_shape = a.shape[:2] + tuple(out_spatial)
        if mode == "nearest" or not align_corners:
            return jax.image.resize(a, out_shape, jmode).astype(a.dtype)
        # align_corners resize via explicit coordinate map
        spatial_axes = list(range(2, a.ndim)) if not channels_last else \
            list(range(1, a.ndim - 1))
        out = a
        for ax, osz in zip(spatial_axes, out_spatial):
            isz = out.shape[ax]
            if isz == osz:
                continue
            idx = jnp.linspace(0.0, isz - 1, osz)
            lo = jnp.clip(jnp.floor(idx).astype(jnp.int32), 0, isz - 1)
            hi = jnp.clip(lo + 1, 0, isz - 1)
            w = (idx - lo).astype(a.dtype)
            shape = [1] * out.ndim
            shape[ax] = osz
            w = w.reshape(shape)
            out = (jnp.take(out, lo, axis=ax) * (1 - w) +
                   jnp.take(out, hi, axis=ax) * w)
        return out.astype(a.dtype)

    return apply(prim, x, op_name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    x1, x2, weight = ensure_tensor(x1), ensure_tensor(x2), ensure_tensor(weight)

    def prim(a, b, w):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        return out

    out = apply(prim, x1, x2, weight, op_name="bilinear")
    if bias is not None:
        out = out + ensure_tensor(bias)
    return out


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    x1, x2 = ensure_tensor(x1), ensure_tensor(x2)

    def prim(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)

    return apply(prim, x1, x2, op_name="cosine_similarity")


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    r = upscale_factor

    def prim(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))

    return apply(prim, x, op_name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    r = downscale_factor

    def prim(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h // r, w // r, c * r * r)

    return apply(prim, x, op_name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    x = ensure_tensor(x)

    def prim(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, groups, c // groups, h, w)
            a = a.transpose(0, 2, 1, 3, 4)
            return a.reshape(n, c, h, w)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, groups, c // groups)
        a = a.transpose(0, 1, 2, 4, 3)
        return a.reshape(n, h, w, c)

    return apply(prim, x, op_name="channel_shuffle")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = ensure_tensor(x)

    def prim(a):
        nrm = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(nrm, epsilon)

    return apply(prim, x, op_name="normalize")
