"""Normalization functionals (ref: `python/paddle/nn/functional/norm.py`;
`phi/kernels/gpu/batch_norm_kernel.cu`, `layer_norm_kernel.cu` -> fused XLA graphs).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply, no_grad
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.common import ensure_tensor


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None,
               name=None):
    x = ensure_tensor(x)
    channels_last = data_format.endswith("C") and len(data_format) > 2
    ch_axis = (x.ndim - 1) if channels_last else (1 if x.ndim > 1 else 0)
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_batch_stats = training and not use_global_stats

    inputs = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        inputs.append(ensure_tensor(weight))
    if has_b:
        inputs.append(ensure_tensor(bias))

    if use_batch_stats:
        def prim(a, *wb):
            m = jnp.mean(a, axis=reduce_axes)
            v = jnp.var(a, axis=reduce_axes)
            shape = [1] * a.ndim
            shape[ch_axis] = a.shape[ch_axis]
            out = (a - m.reshape(shape)) / jnp.sqrt(v.reshape(shape) + epsilon)
            it = iter(wb)
            if has_w:
                out = out * next(it).reshape(shape)
            if has_b:
                out = out + next(it).reshape(shape)
            return out, m, v

        out, batch_mean, batch_var = apply(prim, *inputs, op_name="batch_norm")
        # update running stats out-of-graph (matches reference in-place update)
        if running_mean is not None:
            with no_grad():
                n = int(np.prod([x.shape[i] for i in reduce_axes]))
                unbiased = batch_var._data * (n / max(n - 1, 1))
                running_mean._write(momentum * running_mean._read() +
                                    (1 - momentum) * batch_mean._data)
                running_var._write(momentum * running_var._read() +
                                   (1 - momentum) * unbiased)
        return out

    rm, rv = ensure_tensor(running_mean), ensure_tensor(running_var)

    def prim(a, m, v, *wb):
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        out = (a - m.reshape(shape)) / jnp.sqrt(v.reshape(shape) + epsilon)
        it = iter(wb)
        if has_w:
            out = out * next(it).reshape(shape)
        if has_b:
            out = out + next(it).reshape(shape)
        return out

    return apply(prim, x, rm, rv, *inputs[1:], op_name="batch_norm")


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    x = ensure_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_norm = len(list(normalized_shape))
    axes = tuple(range(x.ndim - n_norm, x.ndim))

    inputs = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        inputs.append(ensure_tensor(weight))
    if has_b:
        inputs.append(ensure_tensor(bias))

    def prim(a, *wb):
        # stats in f32 regardless of input dtype (bf16-safe normalization, the
        # fused-LN convention: bf16 in/out, f32 internal — ref layer_norm CUDA
        # kernels accumulate in float)
        a32 = a.astype(jnp.float32)
        m = jnp.mean(a32, axis=axes, keepdims=True)
        v = jnp.var(a32, axis=axes, keepdims=True)
        out = (a32 - m) / jnp.sqrt(v + epsilon)
        it = iter(wb)
        if has_w:
            out = out * next(it).astype(jnp.float32)
        if has_b:
            out = out + next(it).astype(jnp.float32)
        return out.astype(a.dtype)

    return apply(prim, *inputs, op_name="layer_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW",
                  name=None):
    x = ensure_tensor(x)
    axes = tuple(range(2, x.ndim))
    inputs = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        inputs.append(ensure_tensor(weight))
    if has_b:
        inputs.append(ensure_tensor(bias))

    def prim(a, *wb):
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) / jnp.sqrt(v + eps)
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        it = iter(wb)
        if has_w:
            out = out * next(it).reshape(shape)
        if has_b:
            out = out + next(it).reshape(shape)
        return out

    return apply(prim, *inputs, op_name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = ensure_tensor(x)
    channels_last = data_format.endswith("C") and len(data_format) > 2
    inputs = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        inputs.append(ensure_tensor(weight))
    if has_b:
        inputs.append(ensure_tensor(bias))

    def prim(a, *wb):
        src = jnp.moveaxis(a, -1, 1) if channels_last else a
        n, c = src.shape[0], src.shape[1]
        spatial = src.shape[2:]
        g = src.reshape((n, num_groups, c // num_groups) + spatial)
        axes = tuple(range(2, g.ndim))
        m = jnp.mean(g, axis=axes, keepdims=True)
        v = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - m) / jnp.sqrt(v + epsilon)).reshape(src.shape)
        shape = [1, c] + [1] * len(spatial)
        it = iter(wb)
        if has_w:
            out = out * next(it).reshape(shape)
        if has_b:
            out = out + next(it).reshape(shape)
        return jnp.moveaxis(out, 1, -1) if channels_last else out

    return apply(prim, *inputs, op_name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    x = ensure_tensor(x)
    channels_last = data_format.endswith("C") and len(data_format) > 2

    def prim(a):
        src = jnp.moveaxis(a, -1, 1) if channels_last else a
        sq = src * src
        c = src.shape[1]
        half = size // 2
        pad = [(0, 0)] * src.ndim
        pad[1] = (half, size - half - 1)
        padded = jnp.pad(sq, pad)
        acc = jnp.zeros_like(src)
        for i in range(size):
            acc = acc + jnp.take(padded, jnp.arange(i, i + c), axis=1)
        out = src / jnp.power(k + alpha * acc / size, beta)
        return jnp.moveaxis(out, 1, -1) if channels_last else out

    return apply(prim, x, op_name="local_response_norm")


def spectral_norm(weight, weight_u, weight_v, dim=0, power_iters=1, eps=1e-12,
                  name=None):
    weight = ensure_tensor(weight)
    u, v = ensure_tensor(weight_u), ensure_tensor(weight_v)

    def prim(w, u0, v0):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        uu, vv = u0, v0
        for _ in range(power_iters):
            vv = wm.T @ uu
            vv = vv / (jnp.linalg.norm(vv) + eps)
            uu = wm @ vv
            uu = uu / (jnp.linalg.norm(uu) + eps)
        sigma = uu @ wm @ vv
        return w / sigma

    return apply(prim, weight, u, v, op_name="spectral_norm")
