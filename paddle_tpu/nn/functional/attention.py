"""Attention functionals.

`scaled_dot_product_attention` routes to the Pallas flash-attention kernel on TPU
when shapes allow (ref counterpart: `paddle/fluid/operators/fused/fused_attention_op.cu`
which uses non-flash fmha_ref.h — flash here is strictly better), with an XLA
fallback that fuses fine for short sequences.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply
from paddle_tpu.ops.common import ensure_tensor


def _sdpa_xla(q, k, v, mask, dropout_p, is_causal, scale):
    # q,k,v: [B, S, H, D] (paddle convention)
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * s
    if is_causal:
        qlen, klen = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((qlen, klen), bool), k=klen - qlen)
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, scale=None, training=True,
                                 name=None):
    query, key, value = (ensure_tensor(query), ensure_tensor(key),
                         ensure_tensor(value))
    use_flash = attn_mask is None and dropout_p == 0.0
    if use_flash:
        # no blanket except here: a broken kernel must surface, not silently
        # fall back to O(S^2)-materializing attention (cost a whole round once)
        from paddle_tpu.kernels.flash_attention import flash_attention_fn
        fn = flash_attention_fn(causal=is_causal, scale=scale)
        return apply(fn, query, key, value, op_name="flash_attention",
                     x64_off=True)
    ts = [query, key, value]
    has_mask = attn_mask is not None
    if has_mask:
        ts.append(ensure_tensor(attn_mask))

    def prim(q, k, v, *m):
        return _sdpa_xla(q, k, v, m[0] if m else None, dropout_p, is_causal, scale)

    return apply(prim, *ts, op_name="scaled_dot_product_attention")


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    ml = int(maxlen) if maxlen is not None else int(jnp.max(x._data))
    from paddle_tpu.core import dtype as dtype_mod
    d = dtype_mod.convert_dtype(dtype)
    return apply(lambda a: (jnp.arange(ml) < a[..., None]).astype(d), x,
                 op_name="sequence_mask")
