"""Attention functionals.

`scaled_dot_product_attention` routes to the Pallas flash-attention kernel on TPU
when shapes allow (ref counterpart: `paddle/fluid/operators/fused/fused_attention_op.cu`
which uses non-flash fmha_ref.h — flash here is strictly better), with an XLA
fallback that fuses fine for short sequences.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply
from paddle_tpu.ops.common import ensure_tensor


def _sdpa_xla(q, k, v, mask, dropout_p, is_causal, scale, rng_key=None):
    # q,k,v: [B, S, H, D] (paddle convention)
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * s
    if is_causal:
        qlen, klen = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((qlen, klen), bool), k=klen - qlen)
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and rng_key is not None:
        keep = jax.random.bernoulli(rng_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p),
                          jnp.zeros_like(probs))
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def sequence_parallel_attention(query, key, value, is_causal=True, scale=None,
                                impl="ring", dropout_p=0.0, training=True,
                                name=None):
    """Context-parallel attention over the 'sp' mesh axis — the ONE
    authoritative gate for ring/Ulysses dispatch (beyond-reference feature,
    SURVEY §5.7). [B, S, H, D] layout. Falls back to
    scaled_dot_product_attention when no sp axis is active; RAISES on
    configurations that would silently degrade (attention dropout in training,
    non-divisible seq/heads) instead of quietly gathering full K/V."""
    from paddle_tpu.distributed.mesh import get_mesh
    query, key, value = (ensure_tensor(query), ensure_tensor(key),
                         ensure_tensor(value))
    mesh = get_mesh()
    sp = (mesh.shape.get("sp", 1) if mesh is not None
          and "sp" in mesh.axis_names else 1)
    if sp <= 1 or impl in (None, "none"):
        return scaled_dot_product_attention(
            query, key, value, dropout_p=dropout_p, is_causal=is_causal,
            scale=scale, training=training)
    if dropout_p > 0.0 and training:
        raise RuntimeError(
            "sequence-parallel attention does not support attention dropout "
            "(set attention_dropout=0, or sp_attention='none'); refusing to "
            "silently fall back to full-K/V attention")
    S, H = query.shape[1], query.shape[2]
    if S % sp:
        raise ValueError(f"sequence length {S} not divisible by sp={sp}")
    if impl == "ulysses" and H % sp:
        raise ValueError(f"ulysses needs heads ({H}) divisible by sp ({sp})")
    if impl not in ("ring", "ulysses", "auto"):
        raise ValueError(
            f"unknown sequence-parallel attention impl {impl!r}; "
            "choose 'ring', 'ulysses', or 'none'")
    from paddle_tpu.kernels import registry
    from paddle_tpu.kernels.ring_attention import (
        ring_attention, ulysses_attention)
    # registry-routed (kernels/registry.py): the op validates viability
    # (ulysses needs heads % sp == 0) and counts
    # kernel.dispatch.sp_attention.{ring|ulysses}; "auto" picks the first
    # viable candidate (ring — correct for every shape)
    impl = registry.dispatch("sp_attention", forced=impl,
                             ctx={"heads": H, "sp": sp})
    kern = {"ring": ring_attention, "ulysses": ulysses_attention}[impl]

    def prim(qa, ka, va):
        qt, kt, vt = (jnp.swapaxes(a, 1, 2) for a in (qa, ka, va))
        o = kern(qt, kt, vt, is_causal, scale, mesh)
        return jnp.swapaxes(o, 1, 2)

    return apply(prim, query, key, value, op_name=f"{impl}_attention")


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, scale=None, training=True,
                                 name=None):
    query, key, value = (ensure_tensor(query), ensure_tensor(key),
                         ensure_tensor(value))
    use_flash = attn_mask is None and dropout_p == 0.0
    if use_flash:
        # no blanket except here: a broken kernel must surface, not silently
        # fall back to O(S^2)-materializing attention (cost a whole round once)
        from paddle_tpu.kernels.flash_attention import flash_attention_fn
        fn = flash_attention_fn(causal=is_causal, scale=scale)
        return apply(fn, query, key, value, op_name="flash_attention",
                     x64_off=True)
    ts = [query, key, value]
    has_mask = attn_mask is not None
    if has_mask:
        ts.append(ensure_tensor(attn_mask))
    use_drop = dropout_p > 0.0 and training
    if use_drop:
        # rng key split OUTSIDE the prim (the stateful generator advance must
        # happen at the framework level so capture threads it as state)
        from paddle_tpu.ops.random import default_generator
        from paddle_tpu.core.tensor import Tensor
        ts.append(Tensor(default_generator().next_key(), _internal=True))

    def prim(q, k, v, *rest):
        rest = list(rest)
        rkey = rest.pop() if use_drop else None
        m = rest[0] if rest else None
        return _sdpa_xla(q, k, v, m, dropout_p if use_drop else 0.0,
                         is_causal, scale, rng_key=rkey)

    return apply(prim, *ts, op_name="scaled_dot_product_attention")


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    ml = int(maxlen) if maxlen is not None else int(jnp.max(x._data))
    from paddle_tpu.core import dtype as dtype_mod
    d = dtype_mod.convert_dtype(dtype)
    return apply(lambda a: (jnp.arange(ml) < a[..., None]).astype(d), x,
                 op_name="sequence_mask")
