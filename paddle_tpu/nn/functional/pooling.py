"""Pooling functionals (ref: `python/paddle/nn/functional/pooling.py`;
`phi/kernels/funcs/pooling.cu` -> `lax.reduce_window`)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply
from paddle_tpu.ops.common import ensure_tensor


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    p = list(padding)
    if len(p) == n:
        return [(int(q), int(q)) for q in p]
    if len(p) == 2 * n:
        return [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(n)]
    return [tuple(int(q) for q in pair) for pair in p]


def _pool(x, ksize, stride, padding, n_spatial, data_format, kind,
          ceil_mode=False, exclusive=True, count_include_pad=False):
    x = ensure_tensor(x)
    k = _tuple(ksize, n_spatial)
    s = _tuple(stride if stride is not None else ksize, n_spatial)
    pads = _pads(padding, n_spatial)
    channels_last = data_format.endswith("C")
    if channels_last:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pad_full = ([(0, 0)] + list(pads) + [(0, 0)]) if not isinstance(pads, str) \
            else pads
    else:
        window = (1, 1) + k
        strides = (1, 1) + s
        pad_full = ([(0, 0), (0, 0)] + list(pads)) if not isinstance(pads, str) \
            else pads

    if ceil_mode and not isinstance(pad_full, str):
        # extend high padding so truncated windows are kept
        spatial_dims = range(1, 1 + n_spatial) if channels_last else \
            range(2, 2 + n_spatial)
        pad_full = list(pad_full)
        for i, d in enumerate(spatial_dims):
            size = x.shape[d] + pads[i][0] + pads[i][1]
            rem = (size - k[i]) % s[i]
            if rem != 0:
                lo, hi = pad_full[d]
                pad_full[d] = (lo, hi + (s[i] - rem))

    def prim(a):
        if kind == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else \
                jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, init, jax.lax.max, window, strides,
                                         pad_full)
        # avg
        ones = jnp.ones_like(a)
        summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides,
                                       pad_full)
        if exclusive and not count_include_pad:
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides,
                                           pad_full)
        else:
            counts = float(np.prod(k))
        return (summed / counts).astype(a.dtype)

    return apply(prim, x, op_name=f"{kind}_pool{n_spatial}d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    out = _pool(x, kernel_size, stride, padding, 1, fmt, "max", ceil_mode)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 1, fmt)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, data_format, "max", ceil_mode)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 2, data_format)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, data_format, "max", ceil_mode)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 3, data_format)
    return out


def _pool_mask(x, out, ksize, stride, padding, n_spatial, data_format):
    """Indices of max elements (flat spatial index), computed via comparison."""
    x, out = ensure_tensor(x), ensure_tensor(out)
    k = _tuple(ksize, n_spatial)
    s = _tuple(stride if stride is not None else ksize, n_spatial)

    def prim(a, o):
        # brute-force: for each output pos, recompute argmax via one-hot trick
        n, c = a.shape[0], a.shape[1]
        spatial = a.shape[2:]
        flat_idx = jnp.arange(int(np.prod(spatial))).reshape(spatial)
        idx = jnp.broadcast_to(flat_idx, a.shape).astype(jnp.float64)
        # large-negative trick: reduce-window argmax = max over (value*K + index)
        K = 1e9
        packed = a.astype(jnp.float64) * K - idx
        window = (1, 1) + k
        strides = (1, 1) + s
        pads = _pads(padding, n_spatial)
        pad_full = [(0, 0), (0, 0)] + list(pads)
        best = jax.lax.reduce_window(packed, -jnp.inf, jax.lax.max, window,
                                     strides, pad_full)
        recovered = (-(best - jax.lax.reduce_window(
            a.astype(jnp.float64) * K, -jnp.inf, jax.lax.max, window, strides,
            pad_full)))
        return recovered.astype(jnp.int64)

    return apply(prim, x, out, op_name="max_pool_mask")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _pool(x, kernel_size, stride, padding, 1, fmt, "avg", ceil_mode,
                 exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "avg", ceil_mode,
                 exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "avg", ceil_mode,
                 exclusive)


def _adaptive_pool(x, output_size, n_spatial, data_format, kind):
    x = ensure_tensor(x)
    channels_last = data_format.endswith("C")
    out_sz = _tuple(output_size, n_spatial)
    in_spatial = tuple(x.shape[1:-1] if channels_last else x.shape[2:])
    out_sz = tuple(o if o is not None else i for o, i in zip(out_sz, in_spatial))

    def prim(a):
        src = a if not channels_last else jnp.moveaxis(a, -1, 1)
        for d, (isz, osz) in enumerate(zip(in_spatial, out_sz)):
            ax = 2 + d
            # adaptive windows: start = floor(i*isz/osz), end = ceil((i+1)*isz/osz)
            starts = (np.arange(osz) * isz) // osz
            ends = -(-((np.arange(osz) + 1) * isz) // osz)
            pieces = []
            for st, en in zip(starts, ends):
                seg = jax.lax.slice_in_dim(src, int(st), int(en), axis=ax)
                if kind == "max":
                    pieces.append(jnp.max(seg, axis=ax, keepdims=True))
                else:
                    pieces.append(jnp.mean(seg, axis=ax, keepdims=True))
            src = jnp.concatenate(pieces, axis=ax)
        return src if not channels_last else jnp.moveaxis(src, 1, -1)

    return apply(prim, x, op_name=f"adaptive_{kind}_pool{n_spatial}d")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, "avg")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, "avg")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 1, "NCW", "max")
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 2, "NCHW", "max")
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 3, "NCDHW", "max")
    return (out, None) if return_mask else out
