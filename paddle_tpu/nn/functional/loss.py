"""Loss functionals (ref: `python/paddle/nn/functional/loss.py`).

`cross_entropy` fuses log_softmax+gather like the reference's
`softmax_with_cross_entropy` kernel (`phi/kernels/gpu/cross_entropy_kernel.cu`);
the tensor-parallel variant lives in distributed (≈ `c_softmax_with_cross_entropy`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.common import ensure_tensor


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    has_w = weight is not None
    ts = [input, label] + ([ensure_tensor(weight)] if has_w else [])

    def prim(logits, lab, *w):
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits, 1e-15))
        nclass = logits.shape[axis]
        if soft_label or (lab.ndim == logits.ndim and lab.shape == logits.shape):
            soft = lab
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / nclass
            loss = -jnp.sum(soft * logp, axis=axis)
        else:
            li = lab
            if li.ndim == logits.ndim:
                li = jnp.squeeze(li, axis=axis)
            li = li.astype(jnp.int32)
            valid = li != ignore_index
            safe = jnp.where(valid, li, 0)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe, axis), axis=axis)
            picked = jnp.squeeze(picked, axis)
            if label_smoothing > 0:
                smooth_loss = -jnp.mean(logp, axis=axis)
                loss = -(1 - label_smoothing) * picked + \
                    label_smoothing * smooth_loss
            else:
                loss = -picked
            if w:
                wv = jnp.take(w[0], safe)
                loss = loss * wv
                if reduction == "mean":
                    denom = jnp.sum(jnp.where(valid, wv, 0.0))
                    return jnp.sum(jnp.where(valid, loss, 0.0)) / denom
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1)
        return _reduce(loss, reduction)

    return apply(prim, *ts, op_name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss = loss.unsqueeze(axis)
    if return_softmax:
        from paddle_tpu.nn.functional.activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    has_w = weight is not None
    ts = [input, label] + ([ensure_tensor(weight)] if has_w else [])

    def prim(logp, lab, *w):
        li = lab.astype(jnp.int32)
        valid = li != ignore_index
        safe = jnp.where(valid, li, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1)
        loss = -jnp.squeeze(picked, 1)
        if w:
            wv = jnp.take(w[0], safe)
            loss = loss * wv
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.sum(jnp.where(valid, wv, 0.0))
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1)
        return _reduce(loss, reduction)

    return apply(prim, *ts, op_name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply(lambda a, b: _reduce((a - b) ** 2, reduction), input, label,
                 op_name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label,
                 op_name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def prim(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)

    return apply(prim, input, label, op_name="smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    has_w = weight is not None
    ts = [input, label] + ([ensure_tensor(weight)] if has_w else [])

    def prim(p, y, *w):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    return apply(prim, *ts, op_name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    logit, label = ensure_tensor(logit), ensure_tensor(label)
    ts = [logit, label]
    has_w = weight is not None
    has_pw = pos_weight is not None
    if has_w:
        ts.append(ensure_tensor(weight))
    if has_pw:
        ts.append(ensure_tensor(pos_weight))

    def prim(z, y, *rest):
        it = iter(rest)
        w = next(it) if has_w else None
        pw = next(it) if has_pw else None
        max_val = jnp.clip(-z, 0, None)
        if pw is not None:
            log_wt = (pw - 1) * y + 1
            loss = (1 - y) * z + log_wt * (jnp.log(
                jnp.exp(-max_val) + jnp.exp(-z - max_val)) + max_val)
        else:
            loss = (1 - y) * z + max_val + jnp.log(
                jnp.exp(-max_val) + jnp.exp(-z - max_val))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    return apply(prim, *ts, op_name="binary_cross_entropy_with_logits")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    logit, label = ensure_tensor(logit), ensure_tensor(label)
    ts = [logit, label] + ([ensure_tensor(normalizer)]
                           if normalizer is not None else [])

    def prim(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = jnp.clip(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)

    return apply(prim, *ts, op_name="sigmoid_focal_loss")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def prim(logp, y):
        if log_target:
            loss = jnp.exp(y) * (y - logp)
        else:
            loss = y * (jnp.log(jnp.maximum(y, 1e-12)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return apply(prim, input, label, op_name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    input, other, label = (ensure_tensor(input), ensure_tensor(other),
                           ensure_tensor(label))
    return apply(lambda a, b, y: _reduce(
        jnp.clip(-y * (a - b) + margin, 0, None), reduction),
        input, other, label, op_name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply(lambda a, y: _reduce(jnp.where(
        y == 1, a, jnp.clip(margin - a, 0, None)), reduction),
        input, label, op_name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    input1, input2, label = (ensure_tensor(input1), ensure_tensor(input2),
                             ensure_tensor(label))

    def prim(a, b, y):
        cos = jnp.sum(a * b, -1) / (jnp.linalg.norm(a, axis=-1) *
                                    jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.clip(cos - margin, 0, None))
        return _reduce(loss, reduction)

    return apply(prim, input1, input2, label, op_name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    input, positive, negative = (ensure_tensor(input), ensure_tensor(positive),
                                 ensure_tensor(negative))

    def prim(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v) ** p + epsilon, axis=-1) ** (1.0 / p)
        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_pn = dist(pos, neg)
            d_an = jnp.minimum(d_an, d_pn)
        return _reduce(jnp.clip(d_ap - d_an + margin, 0, None), reduction)

    return apply(prim, input, positive, negative, op_name="triplet_margin_loss")


def square_error_cost(input, label):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply(lambda a, b: (a - b) ** 2, input, label,
                 op_name="square_error_cost")


def log_loss(input, label, epsilon=1e-4, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply(lambda p, y: -y * jnp.log(p + epsilon) -
                 (1 - y) * jnp.log(1 - p + epsilon), input, label,
                 op_name="log_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC (ref `warpctc` integration) via a scan over the alpha lattice."""
    log_probs = ensure_tensor(log_probs)
    labels = ensure_tensor(labels)
    input_lengths = ensure_tensor(input_lengths)
    label_lengths = ensure_tensor(label_lengths)

    def prim(lp, lab, in_len, lab_len):
        # lp: [T, B, C] logits -> log-softmax
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, B, C = lp.shape
        S = lab.shape[1]
        ext = 2 * S + 1
        NEG = -1e30
        # extended label sequence: blank l1 blank l2 ... blank
        ext_lab = jnp.full((B, ext), blank, jnp.int32)
        ext_lab = ext_lab.at[:, 1::2].set(lab.astype(jnp.int32))
        same_as_prev2 = jnp.concatenate(
            [jnp.zeros((B, 2), bool),
             ext_lab[:, 2:] == ext_lab[:, :-2]], axis=1)

        def emit(t):
            return jnp.take_along_axis(lp[t], ext_lab, axis=1)  # [B, ext]

        alpha0 = jnp.full((B, ext), NEG)
        alpha0 = alpha0.at[:, 0].set(emit(0)[:, 0])
        alpha0 = alpha0.at[:, 1].set(emit(0)[:, 1])

        def step(alpha, t):
            prev1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
            prev2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
            prev2 = jnp.where(same_as_prev2 |
                              (ext_lab == blank), NEG, prev2)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
            new_alpha = merged + emit(t)
            # freeze past input_lengths
            new_alpha = jnp.where(t < in_len[:, None], new_alpha, alpha)
            return new_alpha, None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        end1 = 2 * lab_len.astype(jnp.int32)          # final blank
        end2 = 2 * lab_len.astype(jnp.int32) - 1      # final label
        ll = jnp.logaddexp(
            jnp.take_along_axis(alpha, end1[:, None], axis=1)[:, 0],
            jnp.take_along_axis(alpha, jnp.maximum(end2, 0)[:, None],
                                axis=1)[:, 0])
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len, 1))
        return _reduce(loss, reduction)

    return apply(prim, log_probs, labels, input_lengths, label_lengths,
                 op_name="ctc_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    """Two-class logistic loss over {-1, 1} labels
    (paddle.nn.functional.soft_margin_loss; ref loss.py)."""
    input, label = ensure_tensor(input), ensure_tensor(label)

    def prim(x, y):
        return _reduce(jax.nn.softplus(-y.astype(x.dtype) * x), reduction)

    return apply(prim, input, label, op_name="soft_margin_loss")


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    """Multi-label one-vs-all BCE-with-logits averaged over classes
    (paddle.nn.functional.multi_label_soft_margin_loss)."""
    input, label = ensure_tensor(input), ensure_tensor(label)
    ts = [input, label] + ([ensure_tensor(weight)] if weight is not None else [])

    def prim(x, y, *w):
        y = y.astype(x.dtype)
        per = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        if w:
            per = per * w[0]
        return _reduce(jnp.mean(per, axis=-1), reduction)

    return apply(prim, *ts, op_name="multi_label_soft_margin_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Dice coefficient loss for segmentation
    (paddle.nn.functional.dice_loss; ref loss.py)."""
    input, label = ensure_tensor(input), ensure_tensor(label)

    def prim(x, y):
        num_classes = x.shape[-1]
        oh = jax.nn.one_hot(y.squeeze(-1), num_classes, dtype=x.dtype)
        red = tuple(range(1, x.ndim))
        inter = jnp.sum(x * oh, axis=red)
        union = jnp.sum(x, axis=red) + jnp.sum(oh, axis=red)
        return jnp.mean(1 - (2 * inter) / (union + epsilon))

    return apply(prim, input, label, op_name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """Improved triplet N-pair loss (paddle.nn.functional.npair_loss; ref
    loss.py — cross entropy over anchor@positive.T with label-equality targets
    plus an L2 pull on the embeddings)."""
    anchor, positive = ensure_tensor(anchor), ensure_tensor(positive)
    labels = ensure_tensor(labels)

    def prim(a, p, y):
        y = y.reshape(-1)
        tgt = (y[:, None] == y[None, :]).astype(a.dtype)
        tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
        sim = a @ p.T
        ce = jnp.mean(jnp.sum(-tgt * jax.nn.log_softmax(sim, axis=1), axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1))
                        + jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        return ce + reg

    return apply(prim, anchor, positive, labels, op_name="npair_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None, path_table=None,
                  path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid loss (paddle.nn.functional.hsigmoid_loss; ref
    loss.py / `phi/kernels/hsigmoid_loss_kernel.h`).

    Default mode builds the same complete binary tree as the reference's
    MatrixBitCodeFunctor (`paddle/fluid/operators/math/matrix_bit_code.h`):
    leaf code = label + num_classes; internal node for step j is
    ``(code >> (len-j)) - 1`` and the bit is ``(code >> (len-1-j)) & 1``.
    Custom trees pass `path_table`/`path_code` [N, L] with -1 padding.
    """
    input, label = ensure_tensor(input), ensure_tensor(label)
    weight = ensure_tensor(weight)
    ts = [input, label, weight]
    if bias is not None:
        ts.append(ensure_tensor(bias))
    custom = path_table is not None
    if custom:
        ts += [ensure_tensor(path_table), ensure_tensor(path_code)]
    import math as _math
    max_len = int(_math.ceil(_math.log2(max(num_classes, 2)))) + 1

    def prim(x, y, w, *rest):
        b = rest[0] if bias is not None else None
        if custom:
            table = rest[-2].astype(jnp.int32)
            code = rest[-1].astype(x.dtype)
            mask = (table >= 0).astype(x.dtype)
            nodes = jnp.maximum(table, 0)
        else:
            c = y.reshape(-1).astype(jnp.int32) + num_classes
            length = (jnp.floor(jnp.log2(c.astype(jnp.float32)))).astype(jnp.int32)
            j = jnp.arange(max_len, dtype=jnp.int32)[None, :]
            valid = j < length[:, None]
            shift = jnp.maximum(length[:, None] - j, 0)
            nodes = jnp.where(valid, (c[:, None] >> shift) - 1, 0)
            bits = (c[:, None] >> jnp.maximum(shift - 1, 0)) & 1
            code = bits.astype(x.dtype)
            mask = valid.astype(x.dtype)
        wp = jnp.take(w, nodes, axis=0)                    # [N, L, D]
        pre = jnp.einsum("nd,nld->nl", x, wp)
        if b is not None:
            pre = pre + jnp.take(b.reshape(-1), nodes, axis=0)
        # binary logistic per internal node: label bit = code
        per = jnp.maximum(pre, 0) - pre * code + jnp.log1p(jnp.exp(-jnp.abs(pre)))
        return jnp.sum(per * mask, axis=1, keepdims=True)

    return apply(prim, *ts, op_name="hsigmoid_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    """Combined-margin softmax cross entropy (ArcFace family)
    (paddle.nn.functional.margin_cross_entropy; ref loss.py /
    `c_margin_cross_entropy`): target logit cos(theta) becomes
    ``cos(m1*theta + m2) - m3`` before scaling. Model-parallel classed
    sharding rides GSPMD when logits carry an 'mp' sharding."""
    logits, label = ensure_tensor(logits), ensure_tensor(label)

    def prim(lg, y):
        y = y.reshape(-1)
        n, c = lg.shape
        oh = jax.nn.one_hot(y, c, dtype=lg.dtype)
        cos = jnp.clip(lg, -1.0, 1.0)
        theta = jnp.arccos(cos)
        tgt = jnp.cos(margin1 * theta + margin2) - margin3
        adj = jnp.where(oh > 0, tgt, cos) * scale
        logp = jax.nn.log_softmax(adj, axis=-1)
        loss = -jnp.sum(oh * logp, axis=-1, keepdims=True)
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        if return_softmax:
            return loss, jnp.exp(logp)
        return loss

    return apply(prim, logits, label, op_name="margin_cross_entropy")


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers plus all positives, remapping labels
    (paddle.nn.functional.class_center_sample; ref loss.py /
    `class_center_sample_op.cu`). Eager/host op — sampling is data-dependent
    (the reference also materializes the sampled set on host for the same
    reason); returns (remapped_label, sampled_class_indices)."""
    import zlib

    import numpy as np
    lab = np.asarray(ensure_tensor(label).numpy()).reshape(-1)
    pos = np.unique(lab)
    n_sample = max(int(num_samples), len(pos))
    neg_pool = np.setdiff1d(np.arange(num_classes), pos)
    # crc32 (not hash(): salted per process) so every rank of a model-parallel
    # group samples the same negative set from the same labels
    rng = np.random.RandomState(zlib.crc32(lab.tobytes()) % (2**31))
    extra = rng.choice(neg_pool, size=min(n_sample - len(pos), len(neg_pool)),
                       replace=False) if n_sample > len(pos) else np.array([], np.int64)
    sampled = np.concatenate([pos, np.sort(extra)]).astype(lab.dtype)
    remap = {c: i for i, c in enumerate(sampled)}
    remapped = np.array([remap[c] for c in lab], dtype=lab.dtype)
    return Tensor(remapped), Tensor(sampled)
