"""Spatial-transform functionals (ref: `python/paddle/nn/functional/vision.py` —
affine_grid :26, grid_sample :123; C++ kernels `paddle/phi/kernels/grid_sample_kernel.h`,
`affine_grid_kernel.h`).

TPU design: both ops are pure gather/matmul compositions, so they lower to XLA
gathers instead of the reference's hand-written CUDA samplers.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply
from paddle_tpu.ops.common import ensure_tensor

__all__ = ["affine_grid", "grid_sample"]


def _base_grid(h, w, align_corners, dtype):
    if align_corners:
        xs = jnp.linspace(-1.0, 1.0, w, dtype=dtype)
        ys = jnp.linspace(-1.0, 1.0, h, dtype=dtype)
    else:
        xs = (jnp.arange(w, dtype=dtype) * 2 + 1) / w - 1
        ys = (jnp.arange(h, dtype=dtype) * 2 + 1) / h - 1
    gx, gy = jnp.meshgrid(xs, ys)                      # [h, w]
    return jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)   # [h, w, 3]


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Generate a sampling grid from batched 2x3 affine matrices
    (paddle.nn.functional.affine_grid; ref vision.py:26)."""
    theta = ensure_tensor(theta)
    if isinstance(out_shape, (list, tuple)):
        n, c, h, w = [int(v) for v in out_shape]
    else:
        n, c, h, w = [int(v) for v in np.asarray(out_shape.numpy())]

    def fn(th):
        base = _base_grid(h, w, align_corners, th.dtype)        # [h, w, 3]
        # [n, h, w, 2] = [h, w, 3] @ [n, 1, 3, 2]
        return jnp.einsum("hwk,njk->nhwj", base, th)

    return apply(fn, theta, op_name="affine_grid")


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1) / 2 * (size - 1)
    return ((coord + 1) * size - 1) / 2


def _reflect(x, lo, hi):
    # reflect into [lo, hi] with period 2*(hi-lo)
    rng = hi - lo
    if rng <= 0:
        return jnp.zeros_like(x)
    x = jnp.abs(x - lo) % (2 * rng)
    return lo + jnp.where(x > rng, 2 * rng - x, x)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample input at grid locations (paddle.nn.functional.grid_sample;
    ref vision.py:123). x: [N, C, H, W]; grid: [N, Hg, Wg, 2] in [-1, 1]."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"mode should be 'bilinear' or 'nearest', got {mode}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(
            f"padding_mode should be 'zeros'/'border'/'reflection', got {padding_mode}")
    x, grid = ensure_tensor(x), ensure_tensor(grid)

    def fn(a, g):
        n, c, h, w = a.shape
        gx = _unnormalize(g[..., 0], w, align_corners)          # [n, hg, wg]
        gy = _unnormalize(g[..., 1], h, align_corners)
        if padding_mode == "reflection":
            if align_corners:
                gx, gy = _reflect(gx, 0.0, w - 1.0), _reflect(gy, 0.0, h - 1.0)
            else:
                gx = _reflect(gx, -0.5, w - 0.5)
                gy = _reflect(gy, -0.5, h - 0.5)

        def gather(ix, iy):
            ixc = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
            iyc = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
            flat = a.reshape(n, c, h * w)
            idx = (iyc * w + ixc).reshape(n, 1, -1)             # [n, 1, hg*wg]
            out = jnp.take_along_axis(flat, jnp.broadcast_to(idx, (n, c, idx.shape[-1])),
                                      axis=2)
            out = out.reshape(n, c, *ix.shape[1:])
            if padding_mode == "zeros":
                inb = ((ix >= 0) & (ix <= w - 1) & (iy >= 0) & (iy <= h - 1))
                out = out * inb[:, None].astype(a.dtype)
            return out

        if mode == "nearest":
            return gather(jnp.round(gx), jnp.round(gy))
        x0, y0 = jnp.floor(gx), jnp.floor(gy)
        x1, y1 = x0 + 1, y0 + 1
        wa = ((x1 - gx) * (y1 - gy))[:, None]
        wb = ((x1 - gx) * (gy - y0))[:, None]
        wc = ((gx - x0) * (y1 - gy))[:, None]
        wd = ((gx - x0) * (gy - y0))[:, None]
        return (gather(x0, y0) * wa.astype(a.dtype) + gather(x0, y1) * wb.astype(a.dtype)
                + gather(x1, y0) * wc.astype(a.dtype) + gather(x1, y1) * wd.astype(a.dtype))

    return apply(fn, x, grid, op_name="grid_sample")
