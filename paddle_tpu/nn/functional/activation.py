"""Activation functionals (ref: `python/paddle/nn/functional/activation.py`).

All map to jax.nn / jnp primitives that XLA fuses into surrounding matmuls — the
reference needs dedicated CUDA kernels per activation (`phi/kernels/gpu/activation_kernel.cu`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply
from paddle_tpu.ops.common import ensure_tensor, unary

relu = unary(jax.nn.relu, "relu")
relu6 = unary(lambda a: jnp.clip(a, 0, 6), "relu6")
sigmoid = unary(jax.nn.sigmoid, "sigmoid")
tanh = unary(jnp.tanh, "tanh")
softplus_ = jax.nn.softplus
silu = unary(jax.nn.silu, "silu")
swish = silu
mish = unary(lambda a: a * jnp.tanh(jax.nn.softplus(a)), "mish")
hardswish = unary(lambda a: a * jnp.clip(a + 3, 0, 6) / 6, "hardswish")
hardsigmoid = unary(lambda a: jnp.clip(a / 6 + 0.5, 0, 1), "hardsigmoid")
tanhshrink = unary(lambda a: a - jnp.tanh(a), "tanhshrink")


def relu_(x):
    from paddle_tpu.ops.common import rebind, inplace_guard
    inplace_guard(x)
    return rebind(x, relu(x))


def leaky_relu(x, negative_slope=0.01, name=None):
    x = ensure_tensor(x)
    return apply(lambda a: jax.nn.leaky_relu(a, negative_slope), x,
                 op_name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)

    def prim(a, w):
        if w.size > 1:
            ax = 1 if data_format == "NCHW" else a.ndim - 1
            shape = [1] * a.ndim
            shape[ax] = w.size
            w = w.reshape(shape)
        return jnp.where(a > 0, a, a * w)

    return apply(prim, x, weight, op_name="prelu")


def rrelu(x, lower=0.125, upper=0.333, training=False, name=None):
    x = ensure_tensor(x)
    if training:
        from paddle_tpu.ops.random import default_generator
        key = default_generator().next_key()
        return apply(lambda a: jnp.where(
            a >= 0, a, a * jax.random.uniform(key, a.shape, a.dtype, lower, upper)),
            x, op_name="rrelu")
    mid = (lower + upper) / 2.0
    return apply(lambda a: jnp.where(a >= 0, a, a * mid), x, op_name="rrelu")


def elu(x, alpha=1.0, name=None):
    x = ensure_tensor(x)
    return apply(lambda a: jax.nn.elu(a, alpha), x, op_name="elu")


def elu_(x, alpha=1.0, name=None):
    from paddle_tpu.ops.common import rebind, inplace_guard
    inplace_guard(x)
    return rebind(x, elu(x, alpha))


def celu(x, alpha=1.0, name=None):
    x = ensure_tensor(x)
    return apply(lambda a: jax.nn.celu(a, alpha), x, op_name="celu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    x = ensure_tensor(x)
    return apply(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x,
                 op_name="selu")


def gelu(x, approximate=False, name=None):
    x = ensure_tensor(x)
    return apply(lambda a: jax.nn.gelu(a, approximate=approximate), x,
                 op_name="gelu")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    x = ensure_tensor(x)
    return apply(lambda a: jnp.clip(a, min, max), x, op_name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    x = ensure_tensor(x)
    return apply(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0).astype(a.dtype),
                 x, op_name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    x = ensure_tensor(x)
    return apply(lambda a: jnp.where(a > threshold, a - threshold,
                                     jnp.where(a < -threshold, a + threshold, 0.0)
                                     ).astype(a.dtype), x, op_name="softshrink")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    x = ensure_tensor(x)
    return apply(lambda a: jnp.where(a * beta > threshold, a,
                                     jax.nn.softplus(a * beta) / beta), x,
                 op_name="softplus")


def softsign(x, name=None):
    x = ensure_tensor(x)
    return apply(jax.nn.soft_sign, x, op_name="softsign")


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    x = ensure_tensor(x)
    return apply(lambda a: jnp.where(a > threshold, a, value).astype(a.dtype), x,
                 op_name="thresholded_relu")


def softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    if dtype is not None:
        x = x.astype(dtype)
    return apply(lambda a: jax.nn.softmax(a, axis=axis), x, op_name="softmax")


def softmax_(x, axis=-1, dtype=None, name=None):
    from paddle_tpu.ops.common import rebind, inplace_guard
    inplace_guard(x)
    return rebind(x, softmax(x, axis, dtype))


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    if dtype is not None:
        x = x.astype(dtype)
    return apply(lambda a: jax.nn.log_softmax(a, axis=axis), x,
                 op_name="log_softmax")


def log_sigmoid(x, name=None):
    x = ensure_tensor(x)
    return apply(jax.nn.log_sigmoid, x, op_name="log_sigmoid")


def maxout(x, groups, axis=1, name=None):
    x = ensure_tensor(x)

    def prim(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)

    return apply(prim, x, op_name="maxout")


def glu(x, axis=-1, name=None):
    x = ensure_tensor(x)
    return apply(lambda a: jax.nn.glu(a, axis=axis), x, op_name="glu")


def tanh_(x):
    from paddle_tpu.ops.common import rebind, inplace_guard
    inplace_guard(x)
    return rebind(x, tanh(x))
