"""``paddle.static`` compatibility surface.

The reference's static-graph tower (Program/Executor/CompiledProgram,
`python/paddle/static/`) is deliberately collapsed in this design: `@to_static`
whole-step capture + XLA replaces Program construction + executors (SURVEY §7
architecture stance). What remains here is the API users actually carry across
codebases:

- :class:`InputSpec` — shape/dtype declarations for jit.save / onnx.export
- :func:`data` — builds an InputSpec (static-graph `paddle.static.data` analog)
- amp/save/load passthroughs re-exported from their dygraph homes

Program-building entry points raise with a pointer to the jit equivalent
instead of silently half-working.
"""
from __future__ import annotations

from paddle_tpu.jit.save_load import InputSpec  # noqa: F401
from paddle_tpu.framework.io import save, load  # noqa: F401


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a graph input (ref `paddle.static.data`); returns an InputSpec
    usable with jit.to_static/jit.save/onnx.export."""
    spec = InputSpec(shape=shape, dtype=dtype)
    spec.name = name
    return spec


def _no_static(api):
    def fail(*a, **k):
        raise RuntimeError(
            f"paddle.static.{api} builds static Programs, which this "
            "TPU-native framework replaces with @paddle.jit.to_static "
            "whole-step capture (compiled by XLA). Decorate your train step "
            "instead (see paddle_tpu/jit/static_function.py).")
    fail.__name__ = api
    return fail


Program = _no_static("Program")
program_guard = _no_static("program_guard")
default_main_program = _no_static("default_main_program")
default_startup_program = _no_static("default_startup_program")
Executor = _no_static("Executor")
CompiledProgram = _no_static("CompiledProgram")


def name_scope(prefix=None):
    """Names are cosmetic under XLA; kept as a no-op context (ref
    paddle.static.name_scope)."""
    import contextlib
    return contextlib.nullcontext()


def accuracy(input, label, k=1):
    """ref `paddle.static.accuracy` — same math as paddle.metric.accuracy."""
    from paddle_tpu.metric import accuracy as _acc
    return _acc(input, label, k=k)

# ``paddle.static.nn`` — the control-flow ops are REAL (lax.cond/while
# through the dispatcher, `jit/dy2static.py`); layer builders stay collapsed
import types as _types
from paddle_tpu.jit.dy2static import cond, while_loop  # noqa: F401

nn = _types.SimpleNamespace(cond=cond, while_loop=while_loop)
