"""Vision transforms (ref: `python/paddle/vision/transforms/`) on numpy HWC images."""
from __future__ import annotations

import numbers
import random

import numpy as np

from paddle_tpu.core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


def _as_hwc(img):
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _as_hwc(img).astype(np.float32)
        if arr.dtype == np.uint8 or arr.max() > 1.5:
            arr = arr / 255.0
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr.astype(np.float32))


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        if isinstance(img, Tensor):
            arr = img.numpy()
        else:
            arr = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m = self.mean
            s = self.std
        out = (arr - m) / s
        return Tensor(out.astype(np.float32)) if isinstance(img, Tensor) else \
            out.astype(np.float32)


def _resize_bilinear_np(arr, th, tw):
    """Vectorized half-pixel bilinear resample on numpy (HWC)."""
    h, w = arr.shape[:2]
    a = arr.astype(np.float32)
    ys = (np.arange(th, dtype=np.float32) + 0.5) * (h / th) - 0.5
    xs = (np.arange(tw, dtype=np.float32) + 0.5) * (w / tw) - 0.5
    y0 = np.clip(np.floor(ys), 0, h - 1).astype(np.int64)
    x0 = np.clip(np.floor(xs), 0, w - 1).astype(np.int64)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]
    top = a[y0][:, x0] * (1 - wx) + a[y0][:, x1] * wx
    bot = a[y1][:, x0] * (1 - wx) + a[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        # host-side bilinear: the input pipeline must never bounce per-sample
        # work through the accelerator (PIL's C path for uint8, vectorized
        # numpy otherwise)
        arr = _as_hwc(img)
        th, tw = self.size
        if arr.dtype == np.uint8:
            try:
                from PIL import Image
                if arr.shape[2] in (1, 3, 4):
                    mode_arr = arr[:, :, 0] if arr.shape[2] == 1 else arr
                    out = np.asarray(Image.fromarray(mode_arr).resize(
                        (tw, th), Image.BILINEAR))
                    if out.ndim == 2:
                        out = out[:, :, None]
                    return out
            except Exception:
                pass
        return _resize_bilinear_np(arr, th, tw).astype(arr.dtype)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = _as_hwc(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = _as_hwc(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, int) else self.padding[0]
            arr = np.pad(arr, ((p, p), (p, p), (0, 0)))
        h, w = arr.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _as_hwc(img)[:, ::-1]
        return _as_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _as_hwc(img)[::-1]
        return _as_hwc(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        arr = _as_hwc(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            tw = int(round(np.sqrt(target_area * ar)))
            th = int(round(np.sqrt(target_area / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                crop = arr[i:i + th, j:j + tw]
                return Resize(self.size)._apply_image(crop)
        return Resize(self.size)._apply_image(CenterCrop(
            min(h, w))._apply_image(arr))


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return _as_hwc(img).transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _as_hwc(img).astype(np.float32)
        alpha = 1 + random.uniform(-self.value, self.value)
        return np.clip(arr * alpha, 0, 255).astype(np.uint8)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width]


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _as_hwc(img)
    if isinstance(padding, int):
        padding = (padding, padding, padding, padding)
    l, t, r, b = padding
    return np.pad(arr, ((t, b), (l, r), (0, 0)), constant_values=fill)


def _rgb_to_hsv(rgb):
    """Vectorized RGB[0,1] -> HSV[0,1] (matches colorsys semantics)."""
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = np.max(rgb, axis=-1)
    minc = np.min(rgb, axis=-1)
    v = maxc
    rng = maxc - minc
    s_ = np.where(maxc > 0, rng / np.maximum(maxc, 1e-12), 0.0)
    rngs = np.maximum(rng, 1e-12)
    rc = (maxc - r) / rngs
    gc = (maxc - g) / rngs
    bc = (maxc - b) / rngs
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(rng > 0, (h / 6.0) % 1.0, 0.0)
    return np.stack([h, s_, v], axis=-1)


def _hsv_to_rgb(hsv):
    """Vectorized HSV[0,1] -> RGB[0,1]."""
    h, s_, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s_)
    q = v * (1.0 - s_ * f)
    t = v * (1.0 - s_ * (1.0 - f))
    i = i.astype(np.int64) % 6
    choices = np.stack([
        np.stack([v, t, p], -1), np.stack([q, v, p], -1),
        np.stack([p, v, t], -1), np.stack([p, q, v], -1),
        np.stack([t, p, v], -1), np.stack([v, p, q], -1)], 0)
    return np.take_along_axis(choices, i[None, ..., None], axis=0)[0]


def adjust_brightness(img, brightness_factor):
    """Scale pixel intensities (ref functional.adjust_brightness)."""
    arr = _as_hwc(img).astype(np.float32)
    return np.clip(arr * brightness_factor, 0, 255).astype(np.uint8)


def adjust_contrast(img, contrast_factor):
    """Blend with the mean intensity (ref functional.adjust_contrast)."""
    arr = _as_hwc(img).astype(np.float32)
    mean = arr.mean()
    return np.clip(mean + contrast_factor * (arr - mean), 0, 255).astype(np.uint8)


def adjust_saturation(img, saturation_factor):
    """Blend with the grayscale image (ref functional.adjust_saturation)."""
    arr = _as_hwc(img).astype(np.float32)
    gray = arr @ np.asarray([0.299, 0.587, 0.114], np.float32) \
        if arr.shape[-1] == 3 else arr[..., 0]
    gray = gray[..., None]
    return np.clip(gray + saturation_factor * (arr - gray), 0, 255).astype(np.uint8)


def adjust_hue(img, hue_factor):
    """Rotate hue (ref functional.adjust_hue); hue_factor in [-0.5, 0.5].
    Vectorized numpy HSV round-trip (the data-loading hot path)."""
    arr = _as_hwc(img)
    if arr.shape[-1] != 3:
        return arr
    hsv = _rgb_to_hsv(arr.astype(np.float32) / 255.0)
    hsv[..., 0] = (hsv[..., 0] + hue_factor) % 1.0
    rgb = _hsv_to_rgb(hsv)
    return np.clip(np.round(rgb * 255.0), 0, 255).astype(np.uint8)


def to_grayscale(img, num_output_channels=1):
    arr = _as_hwc(img).astype(np.float32)
    gray = arr @ np.asarray([0.299, 0.587, 0.114], np.float32) \
        if arr.shape[-1] == 3 else arr[..., 0]
    out = gray[..., None]
    if num_output_channels == 3:
        out = np.repeat(out, 3, axis=-1)
    return np.clip(out, 0, 255).astype(np.uint8)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase a rectangle (ref functional.erase)."""
    arr = _as_hwc(img)
    if not inplace:
        arr = arr.copy()
    arr[i:i + h, j:j + w] = v
    return arr


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate an HWC image by `angle` degrees counter-clockwise
    (ref functional.rotate); nearest-neighbor sampling. With ``expand`` the
    output grows to hold the whole rotated image."""
    arr = _as_hwc(img)
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else \
        (center[1], center[0])
    rad = np.deg2rad(angle)
    cos_a, sin_a = np.cos(rad), np.sin(rad)
    if expand:
        oh = int(np.ceil(abs(h * cos_a) + abs(w * sin_a)))
        ow = int(np.ceil(abs(w * cos_a) + abs(h * sin_a)))
        ocy, ocx = (oh - 1) / 2.0, (ow - 1) / 2.0
    else:
        oh, ow, ocy, ocx = h, w, cy, cx
    ys, xs = np.mgrid[0:oh, 0:ow]
    # inverse map: output pixel -> source pixel
    sx = cos_a * (xs - ocx) + sin_a * (ys - ocy) + cx
    sy = -sin_a * (xs - ocx) + cos_a * (ys - ocy) + cy
    sxi = np.round(sx).astype(np.int64)
    syi = np.round(sy).astype(np.int64)
    valid = (sxi >= 0) & (sxi < w) & (syi >= 0) & (syi < h)
    out = np.full((oh, ow) + arr.shape[2:], fill, dtype=arr.dtype)
    out[valid] = arr[syi[valid], sxi[valid]]
    return out


class ContrastTransform(BaseTransform):
    """Random contrast jitter (ref transforms.py:ContrastTransform)."""

    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        return adjust_contrast(img, 1 + random.uniform(-self.value, self.value))


class SaturationTransform(BaseTransform):
    """Random saturation jitter (ref transforms.py:SaturationTransform)."""

    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        return adjust_saturation(img,
                                 1 + random.uniform(-self.value, self.value))


class HueTransform(BaseTransform):
    """Random hue rotation (ref transforms.py:HueTransform); value in [0, 0.5]."""

    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        return adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue (ref transforms.py:ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))
        if hue:
            self.ts.append(HueTransform(hue))

    def _apply_image(self, img):
        order = list(self.ts)
        random.shuffle(order)
        for t in order:
            img = t(img)
        return img


class Grayscale(BaseTransform):
    """RGB -> grayscale with 1 or 3 output channels (ref transforms.py:Grayscale)."""

    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class RandomRotation(BaseTransform):
    """Random rotation by an angle in degrees (ref transforms.py:RandomRotation).
    Nearest-neighbor resampling on the numpy grid (no PIL dependency in the
    hot path)."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return rotate(img, angle, interpolation=self.interpolation,
                      expand=self.expand, center=self.center, fill=self.fill)


class RandomErasing(BaseTransform):
    """Random cutout rectangle (ref transforms.py:RandomErasing)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3), value=0,
                 inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio, self.value = prob, scale, ratio, value
        self.inplace = inplace

    def _apply_image(self, img):
        arr = _as_hwc(img)
        if random.random() > self.prob:
            return arr
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            eh = int(round((target * ar) ** 0.5))
            ew = int(round((target / ar) ** 0.5))
            if eh < h and ew < w:
                top = random.randint(0, h - eh)
                left = random.randint(0, w - ew)
                return erase(arr, top, left, eh, ew, self.value,
                             inplace=self.inplace)
        return arr
