"""Vision transforms (ref: `python/paddle/vision/transforms/`) on numpy HWC images."""
from __future__ import annotations

import numbers
import random

import numpy as np

from paddle_tpu.core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


def _as_hwc(img):
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _as_hwc(img).astype(np.float32)
        if arr.dtype == np.uint8 or arr.max() > 1.5:
            arr = arr / 255.0
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr.astype(np.float32))


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        if isinstance(img, Tensor):
            arr = img.numpy()
        else:
            arr = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m = self.mean
            s = self.std
        out = (arr - m) / s
        return Tensor(out.astype(np.float32)) if isinstance(img, Tensor) else \
            out.astype(np.float32)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = _as_hwc(img)
        import jax
        import jax.numpy as jnp
        out = jax.image.resize(jnp.asarray(arr),
                               (self.size[0], self.size[1], arr.shape[2]),
                               "bilinear")
        return np.asarray(out).astype(arr.dtype)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = _as_hwc(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = _as_hwc(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, int) else self.padding[0]
            arr = np.pad(arr, ((p, p), (p, p), (0, 0)))
        h, w = arr.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _as_hwc(img)[:, ::-1]
        return _as_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _as_hwc(img)[::-1]
        return _as_hwc(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        arr = _as_hwc(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            tw = int(round(np.sqrt(target_area * ar)))
            th = int(round(np.sqrt(target_area / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                crop = arr[i:i + th, j:j + tw]
                return Resize(self.size)._apply_image(crop)
        return Resize(self.size)._apply_image(CenterCrop(
            min(h, w))._apply_image(arr))


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return _as_hwc(img).transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _as_hwc(img).astype(np.float32)
        alpha = 1 + random.uniform(-self.value, self.value)
        return np.clip(arr * alpha, 0, 255).astype(np.uint8)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width]


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _as_hwc(img)
    if isinstance(padding, int):
        padding = (padding, padding, padding, padding)
    l, t, r, b = padding
    return np.pad(arr, ((t, b), (l, r), (0, 0)), constant_values=fill)
