"""Vision datasets (ref: `python/paddle/vision/datasets/`).

Zero-egress environment: datasets read local files when present (same on-disk
formats as the reference) and raise a clear error otherwise. `FakeData` provides
deterministic synthetic data for tests/benchmarks.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from paddle_tpu.io import Dataset


class FakeData(Dataset):
    """Deterministic synthetic images + labels (for tests and warm-up benches)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=10,
                 transform=None, dtype="float32"):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.dtype = dtype

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx)
        img = rng.rand(*self.image_shape).astype(self.dtype)
        label = np.array(rng.randint(0, self.num_classes), np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label


    def __len__(self):
        return self.size


class MNIST(Dataset):
    """MNIST from local idx files (ref: `vision/datasets/mnist.py`)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None,
                 root=os.path.expanduser("~/.cache/paddle_tpu/mnist")):
        self.transform = transform
        name = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(root,
                                                f"{name}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(root,
                                                f"{name}-labels-idx1-ubyte.gz")
        if not (os.path.exists(image_path) and os.path.exists(label_path)):
            raise FileNotFoundError(
                f"MNIST files not found at {image_path}; no network egress — "
                "place idx .gz files locally or use vision.datasets.FakeData")
        with gzip.open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            self.images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        with gzip.open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            self.labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[:, :, None]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


FashionMNIST = MNIST


class Cifar10(Dataset):
    """CIFAR-10 from a local tar.gz (ref: `vision/datasets/cifar.py`)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None,
                 root=os.path.expanduser("~/.cache/paddle_tpu")):
        data_file = data_file or os.path.join(root, "cifar-10-python.tar.gz")
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"CIFAR archive not found at {data_file}; no network egress — "
                "place it locally or use vision.datasets.FakeData")
        self.transform = transform
        images, labels = [], []
        with tarfile.open(data_file) as tar:
            names = [m for m in tar.getmembers()
                     if ("data_batch" in m.name if mode == "train"
                         else "test_batch" in m.name)]
            for m in sorted(names, key=lambda m: m.name):
                d = pickle.load(tar.extractfile(m), encoding="bytes")
                images.append(d[b"data"])
                labels.extend(d[b"labels"])
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].transpose(1, 2, 0).astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None,
                 root=os.path.expanduser("~/.cache/paddle_tpu")):
        data_file = data_file or os.path.join(root, "cifar-100-python.tar.gz")
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"CIFAR-100 archive not found at {data_file}; no egress")
        self.transform = transform
        with tarfile.open(data_file) as tar:
            name = "train" if mode == "train" else "test"
            for m in tar.getmembers():
                if m.name.endswith(name):
                    d = pickle.load(tar.extractfile(m), encoding="bytes")
                    self.images = d[b"data"].reshape(-1, 3, 32, 32)
                    self.labels = np.asarray(d[b"fine_labels"], np.int64)


class DatasetFolder(Dataset):
    """Images under class-named subfolders (ref `vision/datasets/folder.py`)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image
            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError as e:
            raise RuntimeError("PIL unavailable; use .npy images") from e

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder


class Flowers(Dataset):
    """Flowers-102 from a local extracted directory (ref:
    `vision/datasets/flowers.py` — the reference downloads jpg/labels/setid
    .mat archives; no egress here, so point `data_file` at a directory
    containing jpg/ plus imagelabels.npy + setid .npy splits, or any folder
    of class-subdir images via DatasetFolder semantics)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        self.transform = transform
        if data_file is None or not os.path.isdir(data_file):
            raise FileNotFoundError(
                "Flowers needs a local data directory (no network egress): "
                "either the extracted 102flowers layout (jpg/ + "
                "imagelabels.npy + setid_{train,valid,test}.npy) or a "
                "class-per-subdir image folder")
        jpg = os.path.join(data_file, "jpg")
        labels_npy = os.path.join(data_file, "imagelabels.npy")
        if os.path.isdir(jpg) and os.path.exists(labels_npy):
            self._images = sorted(
                os.path.join(jpg, f) for f in os.listdir(jpg)
                if f.lower().endswith((".jpg", ".jpeg", ".png")))
            labels = np.load(labels_npy).astype(np.int64) - 1
            split_npy = os.path.join(data_file, f"setid_{mode}.npy")
            if os.path.exists(split_npy):
                idx = np.load(split_npy).astype(np.int64) - 1
            else:
                idx = np.arange(len(self._images))
            self._images = [self._images[i] for i in idx]
            self._labels = labels[idx]
        else:
            folder = DatasetFolder(data_file, transform=None)
            self._images = [s[0] for s in folder.samples]
            self._labels = np.asarray([s[1] for s in folder.samples], np.int64)

    def __getitem__(self, idx):
        from PIL import Image
        img = np.asarray(Image.open(self._images[idx]).convert("RGB"))
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self._labels[idx])

    def __len__(self):
        return len(self._images)


class VOC2012(Dataset):
    """VOC2012 segmentation pairs from a local VOCdevkit tree (ref:
    `vision/datasets/voc2012.py`; no egress — point `data_file` at
    .../VOC2012 containing JPEGImages/, SegmentationClass/ and
    ImageSets/Segmentation/{train,val,trainval}.txt)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        if data_file is None or not os.path.isdir(data_file):
            raise FileNotFoundError(
                "VOC2012 needs a local VOCdevkit/VOC2012 directory "
                "(no network egress)")
        split = {"train": "train", "test": "val", "valid": "val",
                 "trainval": "trainval"}.get(mode, "train")
        list_file = os.path.join(data_file, "ImageSets", "Segmentation",
                                 f"{split}.txt")
        with open(list_file) as f:
            names = [ln.strip() for ln in f if ln.strip()]
        self._pairs = [
            (os.path.join(data_file, "JPEGImages", n + ".jpg"),
             os.path.join(data_file, "SegmentationClass", n + ".png"))
            for n in names]

    def __getitem__(self, idx):
        from PIL import Image
        img_p, seg_p = self._pairs[idx]
        img = np.asarray(Image.open(img_p).convert("RGB"))
        seg = np.asarray(Image.open(seg_p))
        if self.transform is not None:
            img = self.transform(img)
        return img, seg

    def __len__(self):
        return len(self._pairs)
