"""paddle.vision (ref: `python/paddle/vision/`)."""
from paddle_tpu.vision import models  # noqa: F401
from paddle_tpu.vision import transforms  # noqa: F401
from paddle_tpu.vision import datasets  # noqa: F401
from paddle_tpu.vision import ops  # noqa: F401


def set_image_backend(backend):
    pass


def get_image_backend():
    return "numpy"
