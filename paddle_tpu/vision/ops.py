"""Vision ops (ref: `python/paddle/vision/ops.py` — roi_align, nms, deform_conv;
the CUDA kernels map to jax/XLA compositions)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.common import ensure_tensor


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Host-side NMS (dynamic output; eager-only like the reference CPU path)."""
    b = np.asarray(ensure_tensor(boxes).numpy())
    s = np.asarray(ensure_tensor(scores).numpy()) if scores is not None else \
        np.arange(len(b))[::-1].astype(np.float32)
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep), _internal=True)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0):
    """Encode/decode detection boxes against priors (ref
    `phi/kernels/impl/box_coder.h` semantics, xyxy priors <-> center-size
    deltas)."""
    pb = ensure_tensor(prior_box)
    tb = ensure_tensor(target_box)
    if prior_box_var is None:
        pbv = None
    elif isinstance(prior_box_var, (list, tuple)):
        pbv = jnp.asarray(prior_box_var, jnp.float32)
    else:
        pbv = ensure_tensor(prior_box_var)._data

    norm_off = 0.0 if box_normalized else 1.0

    def _prior_cs(p):
        pw = p[..., 2] - p[..., 0] + norm_off
        ph = p[..., 3] - p[..., 1] + norm_off
        px = p[..., 0] + pw * 0.5
        py = p[..., 1] + ph * 0.5
        return px, py, pw, ph

    if code_type == "encode_center_size":
        def prim(p, t):
            px, py, pw, ph = _prior_cs(p)                 # [M]
            tw = t[..., 2] - t[..., 0] + norm_off         # [N]
            th = t[..., 3] - t[..., 1] + norm_off
            tx = t[..., 0] + tw * 0.5
            ty = t[..., 1] + th * 0.5
            dx = (tx[:, None] - px[None, :]) / pw[None, :]
            dy = (ty[:, None] - py[None, :]) / ph[None, :]
            dw = jnp.log(tw[:, None] / pw[None, :])
            dh = jnp.log(th[:, None] / ph[None, :])
            out = jnp.stack([dx, dy, dw, dh], axis=-1)    # [N, M, 4]
            if pbv is not None:
                out = out / jnp.broadcast_to(pbv, out.shape)
            return out

        return apply(prim, pb, tb, op_name="box_coder")

    if code_type == "decode_center_size":
        def prim(p, t):
            px, py, pw, ph = _prior_cs(p)                 # [M]
            d = t                                         # [N, M, 4] deltas
            if d.ndim == 2:
                d = d[:, None, :]
            if pbv is not None:
                v = pbv
                if v.ndim == 2 and axis == 1:
                    # priors vary along dim 0 when axis=1 — align per-prior
                    # variances with the prior broadcast orientation
                    v = v[:, None, :]
                d = d * jnp.broadcast_to(v, d.shape)
            if axis == 0:
                px_, py_, pw_, ph_ = (a[None, :] for a in (px, py, pw, ph))
            else:
                px_, py_, pw_, ph_ = (a[:, None] for a in (px, py, pw, ph))
            cx = d[..., 0] * pw_ + px_
            cy = d[..., 1] * ph_ + py_
            w = jnp.exp(d[..., 2]) * pw_
            h = jnp.exp(d[..., 3]) * ph_
            return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                              cx + w * 0.5 - norm_off,
                              cy + h * 0.5 - norm_off], axis=-1)

        return apply(prim, pb, tb, op_name="box_coder")

    raise ValueError(f"unknown code_type {code_type!r}")


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    boxes_per_img = np.asarray(ensure_tensor(boxes_num).numpy())
    img_idx = np.repeat(np.arange(len(boxes_per_img)), boxes_per_img)

    def prim(feat, bxs):
        def one_box(b, img_i):
            x1, y1, x2, y2 = b * spatial_scale
            if aligned:
                x1, y1, x2, y2 = x1 - 0.5, y1 - 0.5, x2 - 0.5, y2 - 0.5
            ys = y1 + (jnp.arange(oh) + 0.5) * (y2 - y1) / oh
            xs = x1 + (jnp.arange(ow) + 0.5) * (x2 - x1) / ow
            yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
            fm = feat[img_i]  # [C, H, W]
            H, W = fm.shape[1], fm.shape[2]
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1)
            x1i = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(yy, 0, H - 1) - y0
            wx = jnp.clip(xx, 0, W - 1) - x0
            v00 = fm[:, y0, x0]
            v01 = fm[:, y0, x1i]
            v10 = fm[:, y1i, x0]
            v11 = fm[:, y1i, x1i]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                    v10 * wy * (1 - wx) + v11 * wy * wx)

        outs = [one_box(bxs[i], int(img_idx[i])) for i in range(bxs.shape[0])]
        return jnp.stack(outs) if outs else jnp.zeros(
            (0, feat.shape[1], oh, ow), feat.dtype)

    return apply(prim, x, boxes, op_name="roi_align")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    """Deformable conv v1/v2 (ref `phi/kernels/impl/deformable_conv` ideas):
    bilinear-sample the input at offset-shifted kernel taps, then a dense
    matmul over taps — a gather+matmul composition XLA fuses, instead of the
    reference's custom CUDA im2col."""
    x = ensure_tensor(x)
    offset = ensure_tensor(offset)
    weight = ensure_tensor(weight)
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError("deform_conv2d: groups > 1 not supported")
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation
    ts = [x, offset, weight]
    if mask is not None:
        ts.append(ensure_tensor(mask))
    if bias is not None:
        ts.append(ensure_tensor(bias))

    def prim(xa, off, w, *rest):
        rest = list(rest)
        b_arr = rest.pop() if bias is not None else None
        m_arr = rest.pop() if mask is not None else None
        B, C, H, W = xa.shape
        Cout, Cin, KH, KW = w.shape
        OH = (H + 2 * ph - dh * (KH - 1) - 1) // sh + 1
        OW = (W + 2 * pw - dw * (KW - 1) - 1) // sw + 1
        xp = jnp.pad(xa, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        Hp, Wp = H + 2 * ph, W + 2 * pw
        # base sampling grid per output position and tap: [OH,OW,KH,KW]
        oy = jnp.arange(OH) * sh
        ox = jnp.arange(OW) * sw
        ky = jnp.arange(KH) * dh
        kx = jnp.arange(KW) * dw
        base_y = oy[:, None, None, None] + ky[None, None, :, None]
        base_x = ox[None, :, None, None] + kx[None, None, None, :]
        # offsets: [B, 2*KH*KW, OH, OW] -> dy/dx [B,OH,OW,KH,KW]
        offr = off.reshape(B, KH * KW, 2, OH, OW)
        dy = jnp.moveaxis(offr[:, :, 0], 1, -1).reshape(B, OH, OW, KH, KW)
        dx = jnp.moveaxis(offr[:, :, 1], 1, -1).reshape(B, OH, OW, KH, KW)
        sy = base_y[None] + dy
        sx = base_x[None] + dx
        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        wy = sy - y0
        wx = sx - x0
        def tap(yy, xx):
            # per-tap validity: out-of-bound taps contribute ZERO (reference
            # DmcnIm2colBilinear semantics), not the clamped edge pixel
            valid = ((yy >= 0) & (yy <= Hp - 1) &
                     (xx >= 0) & (xx <= Wp - 1))
            yi = jnp.clip(yy.astype(jnp.int32), 0, Hp - 1)
            xi = jnp.clip(xx.astype(jnp.int32), 0, Wp - 1)
            # gather per batch: [B,C,OH,OW,KH,KW]
            vals = jax.vmap(lambda img, yb, xb: img[:, yb, xb])(xp, yi, xi)
            return vals * valid[:, None]
        v00 = tap(y0, x0)
        v01 = tap(y0, x0 + 1)
        v10 = tap(y0 + 1, x0)
        v11 = tap(y0 + 1, x0 + 1)
        wy_ = wy[:, None]
        wx_ = wx[:, None]
        sampled = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_ +
                   v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)
        if m_arr is not None:          # v2 modulation [B, KH*KW, OH, OW]
            mm = jnp.moveaxis(m_arr.reshape(B, KH * KW, OH, OW), 1, -1)
            mm = mm.reshape(B, OH, OW, KH, KW)
            sampled = sampled * mm[:, None]
        # contract (Cin, KH, KW) with the kernel: -> [B, Cout, OH, OW]
        out = jnp.einsum("bchwyx,ocyx->bohw", sampled, w)
        if b_arr is not None:
            out = out + b_arr[None, :, None, None]
        return out

    return apply(prim, *ts, op_name="deform_conv2d")
