"""Vision ops (ref: `python/paddle/vision/ops.py` — roi_align, nms, deform_conv;
the CUDA kernels map to jax/XLA compositions)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.common import ensure_tensor


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Host-side NMS (dynamic output; eager-only like the reference CPU path)."""
    b = np.asarray(ensure_tensor(boxes).numpy())
    s = np.asarray(ensure_tensor(scores).numpy()) if scores is not None else \
        np.arange(len(b))[::-1].astype(np.float32)
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep), _internal=True)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0):
    """Encode/decode detection boxes against priors (ref
    `phi/kernels/impl/box_coder.h` semantics, xyxy priors <-> center-size
    deltas)."""
    pb = ensure_tensor(prior_box)
    tb = ensure_tensor(target_box)
    if prior_box_var is None:
        pbv = None
    elif isinstance(prior_box_var, (list, tuple)):
        pbv = jnp.asarray(prior_box_var, jnp.float32)
    else:
        pbv = ensure_tensor(prior_box_var)._data

    norm_off = 0.0 if box_normalized else 1.0

    def _prior_cs(p):
        pw = p[..., 2] - p[..., 0] + norm_off
        ph = p[..., 3] - p[..., 1] + norm_off
        px = p[..., 0] + pw * 0.5
        py = p[..., 1] + ph * 0.5
        return px, py, pw, ph

    if code_type == "encode_center_size":
        def prim(p, t):
            px, py, pw, ph = _prior_cs(p)                 # [M]
            tw = t[..., 2] - t[..., 0] + norm_off         # [N]
            th = t[..., 3] - t[..., 1] + norm_off
            tx = t[..., 0] + tw * 0.5
            ty = t[..., 1] + th * 0.5
            dx = (tx[:, None] - px[None, :]) / pw[None, :]
            dy = (ty[:, None] - py[None, :]) / ph[None, :]
            dw = jnp.log(tw[:, None] / pw[None, :])
            dh = jnp.log(th[:, None] / ph[None, :])
            out = jnp.stack([dx, dy, dw, dh], axis=-1)    # [N, M, 4]
            if pbv is not None:
                out = out / jnp.broadcast_to(pbv, out.shape)
            return out

        return apply(prim, pb, tb, op_name="box_coder")

    if code_type == "decode_center_size":
        def prim(p, t):
            px, py, pw, ph = _prior_cs(p)                 # [M]
            d = t                                         # [N, M, 4] deltas
            if d.ndim == 2:
                d = d[:, None, :]
            if pbv is not None:
                v = pbv
                if v.ndim == 2 and axis == 1:
                    # priors vary along dim 0 when axis=1 — align per-prior
                    # variances with the prior broadcast orientation
                    v = v[:, None, :]
                d = d * jnp.broadcast_to(v, d.shape)
            if axis == 0:
                px_, py_, pw_, ph_ = (a[None, :] for a in (px, py, pw, ph))
            else:
                px_, py_, pw_, ph_ = (a[:, None] for a in (px, py, pw, ph))
            cx = d[..., 0] * pw_ + px_
            cy = d[..., 1] * ph_ + py_
            w = jnp.exp(d[..., 2]) * pw_
            h = jnp.exp(d[..., 3]) * ph_
            return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                              cx + w * 0.5 - norm_off,
                              cy + h * 0.5 - norm_off], axis=-1)

        return apply(prim, pb, tb, op_name="box_coder")

    raise ValueError(f"unknown code_type {code_type!r}")


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    boxes_per_img = np.asarray(ensure_tensor(boxes_num).numpy())
    img_idx = np.repeat(np.arange(len(boxes_per_img)), boxes_per_img)

    def prim(feat, bxs):
        def one_box(b, img_i):
            x1, y1, x2, y2 = b * spatial_scale
            if aligned:
                x1, y1, x2, y2 = x1 - 0.5, y1 - 0.5, x2 - 0.5, y2 - 0.5
            ys = y1 + (jnp.arange(oh) + 0.5) * (y2 - y1) / oh
            xs = x1 + (jnp.arange(ow) + 0.5) * (x2 - x1) / ow
            yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
            fm = feat[img_i]  # [C, H, W]
            H, W = fm.shape[1], fm.shape[2]
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1)
            x1i = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(yy, 0, H - 1) - y0
            wx = jnp.clip(xx, 0, W - 1) - x0
            v00 = fm[:, y0, x0]
            v01 = fm[:, y0, x1i]
            v10 = fm[:, y1i, x0]
            v11 = fm[:, y1i, x1i]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                    v10 * wy * (1 - wx) + v11 * wy * wx)

        outs = [one_box(bxs[i], int(img_idx[i])) for i in range(bxs.shape[0])]
        return jnp.stack(outs) if outs else jnp.zeros(
            (0, feat.shape[1], oh, ow), feat.dtype)

    return apply(prim, x, boxes, op_name="roi_align")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    """Deformable conv v1/v2 (ref `phi/kernels/impl/deformable_conv` ideas):
    bilinear-sample the input at offset-shifted kernel taps, then a dense
    matmul over taps — a gather+matmul composition XLA fuses, instead of the
    reference's custom CUDA im2col."""
    x = ensure_tensor(x)
    offset = ensure_tensor(offset)
    weight = ensure_tensor(weight)
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError("deform_conv2d: groups > 1 not supported")
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation
    ts = [x, offset, weight]
    if mask is not None:
        ts.append(ensure_tensor(mask))
    if bias is not None:
        ts.append(ensure_tensor(bias))

    def prim(xa, off, w, *rest):
        rest = list(rest)
        b_arr = rest.pop() if bias is not None else None
        m_arr = rest.pop() if mask is not None else None
        B, C, H, W = xa.shape
        Cout, Cin, KH, KW = w.shape
        OH = (H + 2 * ph - dh * (KH - 1) - 1) // sh + 1
        OW = (W + 2 * pw - dw * (KW - 1) - 1) // sw + 1
        xp = jnp.pad(xa, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        Hp, Wp = H + 2 * ph, W + 2 * pw
        # base sampling grid per output position and tap: [OH,OW,KH,KW]
        oy = jnp.arange(OH) * sh
        ox = jnp.arange(OW) * sw
        ky = jnp.arange(KH) * dh
        kx = jnp.arange(KW) * dw
        base_y = oy[:, None, None, None] + ky[None, None, :, None]
        base_x = ox[None, :, None, None] + kx[None, None, None, :]
        # offsets: [B, 2*KH*KW, OH, OW] -> dy/dx [B,OH,OW,KH,KW]
        offr = off.reshape(B, KH * KW, 2, OH, OW)
        dy = jnp.moveaxis(offr[:, :, 0], 1, -1).reshape(B, OH, OW, KH, KW)
        dx = jnp.moveaxis(offr[:, :, 1], 1, -1).reshape(B, OH, OW, KH, KW)
        sy = base_y[None] + dy
        sx = base_x[None] + dx
        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        wy = sy - y0
        wx = sx - x0
        def tap(yy, xx):
            # per-tap validity: out-of-bound taps contribute ZERO (reference
            # DmcnIm2colBilinear semantics), not the clamped edge pixel
            valid = ((yy >= 0) & (yy <= Hp - 1) &
                     (xx >= 0) & (xx <= Wp - 1))
            yi = jnp.clip(yy.astype(jnp.int32), 0, Hp - 1)
            xi = jnp.clip(xx.astype(jnp.int32), 0, Wp - 1)
            # gather per batch: [B,C,OH,OW,KH,KW]
            vals = jax.vmap(lambda img, yb, xb: img[:, yb, xb])(xp, yi, xi)
            return vals * valid[:, None]
        v00 = tap(y0, x0)
        v01 = tap(y0, x0 + 1)
        v10 = tap(y0 + 1, x0)
        v11 = tap(y0 + 1, x0 + 1)
        wy_ = wy[:, None]
        wx_ = wx[:, None]
        sampled = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_ +
                   v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)
        if m_arr is not None:          # v2 modulation [B, KH*KW, OH, OW]
            mm = jnp.moveaxis(m_arr.reshape(B, KH * KW, OH, OW), 1, -1)
            mm = mm.reshape(B, OH, OW, KH, KW)
            sampled = sampled * mm[:, None]
        # contract (Cin, KH, KW) with the kernel: -> [B, Cout, OH, OW]
        out = jnp.einsum("bchwyx,ocyx->bohw", sampled, w)
        if b_arr is not None:
            out = out + b_arr[None, :, None, None]
        return out

    return apply(prim, *ts, op_name="deform_conv2d")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoI max pooling (paddle.vision.ops.roi_pool; ref `roi_pool` kernel
    `phi/kernels/roi_pool_kernel.h`). x: [N, C, H, W]; boxes: [R, 4] xyxy."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    x, boxes = ensure_tensor(x), ensure_tensor(boxes)
    bn = np.asarray(ensure_tensor(boxes_num).numpy())
    img_of_box = np.repeat(np.arange(len(bn)), bn)

    def prim(feat, bxs):
        H, W = feat.shape[2], feat.shape[3]

        def one_box(b, img_i):
            x1 = jnp.round(b[0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(b[1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.round(b[2] * spatial_scale).astype(jnp.int32)
            y2 = jnp.round(b[3] * spatial_scale).astype(jnp.int32)
            rh = jnp.maximum(y2 - y1 + 1, 1) / ph
            rw = jnp.maximum(x2 - x1 + 1, 1) / pw
            # per output cell max over its bin; vectorize via mask reduction
            ys = jnp.arange(H)[None, :]
            xs = jnp.arange(W)[None, :]
            hstart = jnp.floor(jnp.arange(ph)[:, None] * rh).astype(jnp.int32) + y1
            hend = jnp.ceil((jnp.arange(ph)[:, None] + 1) * rh).astype(jnp.int32) + y1
            wstart = jnp.floor(jnp.arange(pw)[:, None] * rw).astype(jnp.int32) + x1
            wend = jnp.ceil((jnp.arange(pw)[:, None] + 1) * rw).astype(jnp.int32) + x1
            hmask = (ys >= jnp.clip(hstart, 0, H)) & (ys < jnp.clip(hend, 0, H))
            wmask = (xs >= jnp.clip(wstart, 0, W)) & (xs < jnp.clip(wend, 0, W))
            m = hmask[:, None, :, None] & wmask[None, :, None, :]   # [ph,pw,H,W]
            f = feat[img_i]                                         # [C, H, W]
            NEG = jnp.asarray(-3.4e38, f.dtype)
            masked = jnp.where(m[None], f[:, None, None], NEG)
            out = jnp.max(masked, axis=(-2, -1))                    # [C, ph, pw]
            return jnp.where(jnp.any(m, axis=(-2, -1))[None], out,
                             jnp.zeros_like(out))

        return jax.vmap(one_box)(bxs, jnp.asarray(img_of_box))

    return apply(prim, x, boxes, op_name="roi_pool")


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI average pooling (paddle.vision.ops.psroi_pool;
    ref `phi/kernels/psroi_pool_kernel.h`). Input channels = C_out * ph * pw."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    x, boxes = ensure_tensor(x), ensure_tensor(boxes)
    bn = np.asarray(ensure_tensor(boxes_num).numpy())
    img_of_box = np.repeat(np.arange(len(bn)), bn)
    c_in = x.shape[1]
    if c_in % (ph * pw) != 0:
        raise ValueError("input channel must be divisible by output_size^2")
    c_out = c_in // (ph * pw)

    def prim(feat, bxs):
        H, W = feat.shape[2], feat.shape[3]

        def one_box(b, img_i):
            x1 = b[0] * spatial_scale
            y1 = b[1] * spatial_scale
            x2 = b[2] * spatial_scale
            y2 = b[3] * spatial_scale
            rh = jnp.maximum(y2 - y1, 0.1) / ph
            rw = jnp.maximum(x2 - x1, 0.1) / pw
            ys = jnp.arange(H)[None, :]
            xs = jnp.arange(W)[None, :]
            hstart = jnp.floor(jnp.arange(ph)[:, None] * rh + y1).astype(jnp.int32)
            hend = jnp.ceil((jnp.arange(ph)[:, None] + 1) * rh + y1).astype(jnp.int32)
            wstart = jnp.floor(jnp.arange(pw)[:, None] * rw + x1).astype(jnp.int32)
            wend = jnp.ceil((jnp.arange(pw)[:, None] + 1) * rw + x1).astype(jnp.int32)
            hmask = (ys >= jnp.clip(hstart, 0, H)) & (ys < jnp.clip(hend, 0, H))
            wmask = (xs >= jnp.clip(wstart, 0, W)) & (xs < jnp.clip(wend, 0, W))
            m = (hmask[:, None, :, None] & wmask[None, :, None, :]).astype(feat.dtype)
            # channel layout: [c_out * ph * pw] position-sensitive maps
            f = feat[img_i].reshape(c_out, ph, pw, H, W)
            s = jnp.einsum("cijhw,ijhw->cij", f, m)
            cnt = jnp.maximum(jnp.sum(m, axis=(-2, -1)), 1.0)
            return s / cnt

        return jax.vmap(one_box)(bxs, jnp.asarray(img_of_box))

    return apply(prim, x, boxes, op_name="psroi_pool")


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) boxes (paddle.vision.ops.prior_box; ref
    `phi/kernels/prior_box_kernel.h`). Returns (boxes [H,W,P,4],
    variances [H,W,P,4]); pure host computation from static shapes."""
    feat = ensure_tensor(input)
    img = ensure_tensor(image)
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for ms_i, ms in enumerate(min_sizes):
        sizes = []
        if min_max_aspect_ratios_order:
            sizes.append((ms, ms))
            if max_sizes:
                mx = max_sizes[ms_i]
                sizes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                sizes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                sizes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                mx = max_sizes[ms_i]
                sizes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
        boxes.append(np.asarray(sizes))
    sizes = np.concatenate(boxes, axis=0)                       # [P, 2] (w, h)
    cy = (np.arange(fh) + offset) * step_h
    cx = (np.arange(fw) + offset) * step_w
    cxg, cyg = np.meshgrid(cx, cy)                              # [H, W]
    out = np.zeros((fh, fw, len(sizes), 4), np.float32)
    out[..., 0] = (cxg[:, :, None] - sizes[None, None, :, 0] / 2) / iw
    out[..., 1] = (cyg[:, :, None] - sizes[None, None, :, 1] / 2) / ih
    out[..., 2] = (cxg[:, :, None] + sizes[None, None, :, 0] / 2) / iw
    out[..., 3] = (cyg[:, :, None] + sizes[None, None, :, 1] / 2) / ih
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32), out.shape).copy()
    return Tensor(jnp.asarray(out), _internal=True), Tensor(jnp.asarray(var), _internal=True)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5, name=None):
    """Decode YOLOv3 head predictions into boxes+scores
    (paddle.vision.ops.yolo_box; ref `phi/kernels/yolo_box_kernel.h`).
    x: [N, AN*(5+C), H, W] -> (boxes [N, H*W*AN, 4], scores [N, H*W*AN, C])."""
    x, img_size = ensure_tensor(x), ensure_tensor(img_size)
    an = len(anchors) // 2
    anc = np.asarray(anchors, np.float32).reshape(an, 2)

    def prim(a, imgs):
        n, _, h, w = a.shape
        a = a.reshape(n, an, 5 + class_num, h, w)
        gx = (jnp.arange(w)[None, None, None, :])
        gy = (jnp.arange(h)[None, None, :, None])
        sx, sy = scale_x_y, -0.5 * (scale_x_y - 1.0)
        bx = (jax.nn.sigmoid(a[:, :, 0]) * sx + sy + gx) / w
        by = (jax.nn.sigmoid(a[:, :, 1]) * sx + sy + gy) / h
        bw = jnp.exp(a[:, :, 2]) * anc[None, :, 0, None, None] / (downsample_ratio * w)
        bh = jnp.exp(a[:, :, 3]) * anc[None, :, 1, None, None] / (downsample_ratio * h)
        conf = jax.nn.sigmoid(a[:, :, 4])
        if iou_aware:
            conf = conf ** (1 - iou_aware_factor)
        prob = jax.nn.sigmoid(a[:, :, 5:]) * conf[:, :, None]
        ih = imgs[:, 0].astype(a.dtype)[:, None, None, None]
        iw = imgs[:, 1].astype(a.dtype)[:, None, None, None]
        x1 = (bx - bw / 2) * iw
        y1 = (by - bh / 2) * ih
        x2 = (bx + bw / 2) * iw
        y2 = (by + bh / 2) * ih
        if clip_bbox:
            x1 = jnp.clip(x1, 0, iw - 1)
            y1 = jnp.clip(y1, 0, ih - 1)
            x2 = jnp.clip(x2, 0, iw - 1)
            y2 = jnp.clip(y2, 0, ih - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)            # [N, AN, H, W, 4]
        mask = (conf > conf_thresh).astype(a.dtype)
        boxes = boxes * mask[..., None]
        scores = prob * mask[:, :, None]
        boxes = boxes.transpose(0, 2, 3, 1, 4).reshape(n, h * w * an, 4)
        # same (h, w, an) row order as boxes
        scores = scores.transpose(0, 3, 4, 1, 2).reshape(n, h * w * an, class_num)
        return boxes, scores

    return apply(prim, x, img_size, op_name="yolo_box", n_outputs=2)


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (paddle.vision.ops.matrix_nms; ref
    `phi/kernels/matrix_nms_kernel.h`): parallel soft suppression via the
    pairwise IoU matrix. Host/eager op (dynamic output count)."""
    b = np.asarray(ensure_tensor(bboxes).numpy())   # [N, M, 4]
    s = np.asarray(ensure_tensor(scores).numpy())   # [N, C, M]
    outs, idxs, nums = [], [], []
    for i in range(b.shape[0]):
        dets = []
        for c in range(s.shape[1]):
            if c == background_label:
                continue
            sc = s[i, c]
            keep = np.where(sc > score_threshold)[0]
            if len(keep) == 0:
                continue
            order = keep[np.argsort(-sc[keep])][:nms_top_k]
            bx, scs = b[i][order], sc[order]
            x1, y1, x2, y2 = bx[:, 0], bx[:, 1], bx[:, 2], bx[:, 3]
            off = 0.0 if normalized else 1.0
            area = (x2 - x1 + off) * (y2 - y1 + off)
            xx1 = np.maximum(x1[:, None], x1[None, :])
            yy1 = np.maximum(y1[:, None], y1[None, :])
            xx2 = np.minimum(x2[:, None], x2[None, :])
            yy2 = np.minimum(y2[:, None], y2[None, :])
            inter = np.clip(xx2 - xx1 + off, 0, None) * np.clip(yy2 - yy1 + off, 0, None)
            iou = inter / (area[:, None] + area[None, :] - inter + 1e-10)
            iou = np.triu(iou, k=1)
            iou_cmax = iou.max(axis=0)                        # max IoU with higher-scored
            # decay_j = min_i f(iou_ij) / f(iou_cmax_i): denominator indexed by
            # the suppressor row i
            if use_gaussian:
                decay = np.exp((iou_cmax[:, None] ** 2 - iou ** 2) / gaussian_sigma)
            else:
                decay = (1 - iou) / (1 - iou_cmax[:, None] + 1e-10)
            decay = decay.min(axis=0)
            newsc = scs * decay
            sel = np.where(newsc > post_threshold)[0]
            for j in sel:
                dets.append((c, newsc[j], *bx[j], order[j]))
        dets.sort(key=lambda d: -d[1])
        dets = dets[:keep_top_k]
        outs.append(np.asarray([[d[0], d[1], d[2], d[3], d[4], d[5]] for d in dets],
                               np.float32).reshape(-1, 6))
        idxs.append(np.asarray([d[6] + i * b.shape[1] for d in dets], np.int64))
        nums.append(len(dets))
    out = Tensor(jnp.asarray(np.concatenate(outs, 0) if outs else
                             np.zeros((0, 6), np.float32)), _internal=True)
    rois_num = Tensor(jnp.asarray(np.asarray(nums, np.int32)), _internal=True)
    index = Tensor(jnp.asarray(np.concatenate(idxs, 0) if idxs else
                               np.zeros((0,), np.int64)), _internal=True)
    res = [out]
    if return_index:
        res.append(index)
    if return_rois_num:
        res.append(rois_num)
    return tuple(res) if len(res) > 1 else out


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Route RoIs to FPN levels by scale (paddle.vision.ops.
    distribute_fpn_proposals; ref `phi/kernels/distribute_fpn_proposals_kernel.h`).
    Host/eager op; returns (multi_rois list, restore_ind, rois_num_per_level)."""
    rois = np.asarray(ensure_tensor(fpn_rois).numpy())
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.clip(w * h, 0, None))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi, order, nums = [], [], []
    for L in range(min_level, max_level + 1):
        sel = np.where(lvl == L)[0]
        multi.append(Tensor(jnp.asarray(rois[sel]), _internal=True))
        nums.append(Tensor(jnp.asarray(np.asarray([len(sel)], np.int32)), _internal=True))
        order.append(sel)
    order = np.concatenate(order) if order else np.zeros((0,), np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    return multi, Tensor(jnp.asarray(restore.astype(np.int32)), _internal=True), nums


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000, nms_thresh=0.5,
                       min_size=0.1, eta=1.0, pixel_offset=False,
                       return_rois_num=False, name=None):
    """RPN proposal generation (paddle.vision.ops.generate_proposals; ref
    `phi/kernels/generate_proposals_kernel.h`). Host/eager op."""
    sc = np.asarray(ensure_tensor(scores).numpy())          # [N, A, H, W]
    deltas = np.asarray(ensure_tensor(bbox_deltas).numpy()) # [N, 4A, H, W]
    imgs = np.asarray(ensure_tensor(img_size).numpy())      # [N, 2] (h, w)
    anc = np.asarray(ensure_tensor(anchors).numpy()).reshape(-1, 4)
    var = np.asarray(ensure_tensor(variances).numpy()).reshape(-1, 4)
    n, a, h, w = sc.shape
    off = 1.0 if pixel_offset else 0.0
    all_rois, all_nums = [], []
    for i in range(n):
        s = sc[i].transpose(1, 2, 0).reshape(-1)            # [H*W*A]
        d = deltas[i].reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, an, vr = s[order], d[order], anc[order], var[order]
        aw = an[:, 2] - an[:, 0] + off
        ah = an[:, 3] - an[:, 1] + off
        acx = an[:, 0] + aw / 2
        acy = an[:, 1] + ah / 2
        cx = vr[:, 0] * d[:, 0] * aw + acx
        cy = vr[:, 1] * d[:, 1] * ah + acy
        bw = aw * np.exp(np.minimum(vr[:, 2] * d[:, 2], np.log(1000 / 16)))
        bh = ah * np.exp(np.minimum(vr[:, 3] * d[:, 3], np.log(1000 / 16)))
        boxes = np.stack([cx - bw / 2, cy - bh / 2,
                          cx + bw / 2 - off, cy + bh / 2 - off], axis=1)
        ih, iw = imgs[i, 0], imgs[i, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - off)
        keepmask = ((boxes[:, 2] - boxes[:, 0] + off >= min_size) &
                    (boxes[:, 3] - boxes[:, 1] + off >= min_size))
        boxes, s = boxes[keepmask], s[keepmask]
        # plain NMS
        x1, y1, x2, y2 = boxes.T
        area = (x2 - x1 + off) * (y2 - y1 + off)
        keep = []
        idx = np.argsort(-s)
        supp = np.zeros(len(boxes), bool)
        for j in idx:
            if supp[j]:
                continue
            keep.append(j)
            if len(keep) >= post_nms_top_n:
                break
            xx1 = np.maximum(x1[j], x1)
            yy1 = np.maximum(y1[j], y1)
            xx2 = np.minimum(x2[j], x2)
            yy2 = np.minimum(y2[j], y2)
            inter = np.clip(xx2 - xx1 + off, 0, None) * np.clip(yy2 - yy1 + off, 0, None)
            iou = inter / (area[j] + area - inter + 1e-10)
            supp |= iou > nms_thresh
            supp[j] = True
        keep = np.asarray(keep, np.int64)
        all_rois.append(np.concatenate([boxes[keep], s[keep, None]], axis=1))
        all_nums.append(len(keep))
    rois = np.concatenate([r[:, :4] for r in all_rois], 0) if all_rois else \
        np.zeros((0, 4), np.float32)
    roi_scores = np.concatenate([r[:, 4] for r in all_rois], 0) if all_rois else \
        np.zeros((0,), np.float32)
    out = (Tensor(jnp.asarray(rois.astype(np.float32)), _internal=True),
           Tensor(jnp.asarray(roi_scores.astype(np.float32)), _internal=True))
    if return_rois_num:
        return out + (Tensor(jnp.asarray(np.asarray(all_nums, np.int32)),
                             _internal=True),)
    return out


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (paddle.vision.ops.decode_jpeg;
    the reference wraps nvjpeg — here PIL supplies the host decode, matching
    the reference's CPU fallback)."""
    import io
    from PIL import Image
    data = np.asarray(ensure_tensor(x).numpy(), np.uint8).tobytes()
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb", "RGB"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr), _internal=True)


def read_file(filename, name=None):
    """Read a file into a uint8 tensor (paddle.vision.ops.read_file)."""
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data), _internal=True)
