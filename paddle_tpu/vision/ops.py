"""Vision ops (ref: `python/paddle/vision/ops.py` — roi_align, nms, deform_conv;
the CUDA kernels map to jax/XLA compositions)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.common import ensure_tensor


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Host-side NMS (dynamic output; eager-only like the reference CPU path)."""
    b = np.asarray(ensure_tensor(boxes).numpy())
    s = np.asarray(ensure_tensor(scores).numpy()) if scores is not None else \
        np.arange(len(b))[::-1].astype(np.float32)
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep), _internal=True)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0):
    raise NotImplementedError("box_coder: planned (detection tower)")


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    boxes_per_img = np.asarray(ensure_tensor(boxes_num).numpy())
    img_idx = np.repeat(np.arange(len(boxes_per_img)), boxes_per_img)

    def prim(feat, bxs):
        def one_box(b, img_i):
            x1, y1, x2, y2 = b * spatial_scale
            if aligned:
                x1, y1, x2, y2 = x1 - 0.5, y1 - 0.5, x2 - 0.5, y2 - 0.5
            ys = y1 + (jnp.arange(oh) + 0.5) * (y2 - y1) / oh
            xs = x1 + (jnp.arange(ow) + 0.5) * (x2 - x1) / ow
            yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
            fm = feat[img_i]  # [C, H, W]
            H, W = fm.shape[1], fm.shape[2]
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1)
            x1i = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(yy, 0, H - 1) - y0
            wx = jnp.clip(xx, 0, W - 1) - x0
            v00 = fm[:, y0, x0]
            v01 = fm[:, y0, x1i]
            v10 = fm[:, y1i, x0]
            v11 = fm[:, y1i, x1i]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                    v10 * wy * (1 - wx) + v11 * wy * wx)

        outs = [one_box(bxs[i], int(img_idx[i])) for i in range(bxs.shape[0])]
        return jnp.stack(outs) if outs else jnp.zeros(
            (0, feat.shape[1], oh, ow), feat.dtype)

    return apply(prim, x, boxes, op_name="roi_align")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    raise NotImplementedError("deform_conv2d: planned (detection tower)")
