"""Model zoo (ref: `python/paddle/vision/models/__init__.py`)."""
from paddle_tpu.vision.models.lenet import LeNet  # noqa: F401
from paddle_tpu.vision.models.resnet import (  # noqa: F401
    ResNet, BasicBlock, BottleneckBlock, resnet18, resnet34, resnet50, resnet101,
    resnet152, resnext50_32x4d, resnext101_32x4d, wide_resnet50_2,
    wide_resnet101_2,
)
from paddle_tpu.vision.models.vgg import (  # noqa: F401
    VGG, vgg11, vgg13, vgg16, vgg19)
from paddle_tpu.vision.models.mobilenet import (  # noqa: F401
    MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2)
from paddle_tpu.vision.models.alexnet import (  # noqa: F401
    AlexNet, alexnet, SqueezeNet, squeezenet1_0, squeezenet1_1)
from paddle_tpu.vision.models.densenet import (  # noqa: F401
    DenseNet, densenet121, densenet161, densenet169, densenet201, densenet264)
from paddle_tpu.vision.models.googlenet import (  # noqa: F401
    GoogLeNet, googlenet, InceptionV3, inception_v3)
from paddle_tpu.vision.models.shufflenetv2 import (  # noqa: F401
    ShuffleNetV2, shufflenet_v2_x0_25, shufflenet_v2_x0_33, shufflenet_v2_x0_5,
    shufflenet_v2_x1_0, shufflenet_v2_x1_5, shufflenet_v2_x2_0)
from paddle_tpu.vision.models.mobilenetv3 import (  # noqa: F401
    MobileNetV3Small, MobileNetV3Large, mobilenet_v3_small, mobilenet_v3_large)
