"""ShuffleNet v2 (ref: `python/paddle/vision/models/shufflenetv2.py`)."""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _channel_shuffle(x, groups):
    return F.channel_shuffle(x, groups)


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                nn.Conv2D(in_c // 2, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), nn.ReLU(),
                nn.Conv2D(branch_c, branch_c, 3, stride=1, padding=1,
                          groups=branch_c, bias_attr=False),
                nn.BatchNorm2D(branch_c),
                nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), nn.ReLU(),
            )
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1,
                          groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), nn.ReLU(),
            )
            self.branch2 = nn.Sequential(
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), nn.ReLU(),
                nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                          groups=branch_c, bias_attr=False),
                nn.BatchNorm2D(branch_c),
                nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), nn.ReLU(),
            )

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512),
    0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024),
    1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024),
    2.0: (24, 244, 488, 976, 2048),
}


class ShuffleNetV2(nn.Layer):
    """ShuffleNet v2 (ref shufflenetv2.py:ShuffleNetV2)."""

    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stage_repeats = (4, 8, 4)
        out_cs = _STAGE_OUT[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, out_cs[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(out_cs[0]), nn.ReLU(),
        )
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = out_cs[0]
        for i, reps in enumerate(stage_repeats):
            out_c = out_cs[i + 1]
            seq = [_InvertedResidual(in_c, out_c, 2)]
            for _ in range(reps - 1):
                seq.append(_InvertedResidual(out_c, out_c, 1))
            stages.append(nn.Sequential(*seq))
            in_c = out_c
        self.stages = nn.LayerList(stages)
        self.conv5 = nn.Sequential(
            nn.Conv2D(in_c, out_cs[-1], 1, bias_attr=False),
            nn.BatchNorm2D(out_cs[-1]), nn.ReLU(),
        )
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(out_cs[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        for s in self.stages:
            x = s(x)
        x = self.conv5(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(paddle.flatten(x, 1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)
