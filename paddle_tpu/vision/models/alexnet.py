"""AlexNet + SqueezeNet (ref: `python/paddle/vision/models/alexnet.py`,
`squeezenet.py`)."""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = ["AlexNet", "alexnet", "SqueezeNet", "squeezenet1_0",
           "squeezenet1_1"]


class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
                nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(start_axis=1)
            x = self.classifier(x)
        return x


def alexnet(pretrained=False, **kwargs):
    return AlexNet(**kwargs)


class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(in_c, squeeze, 1), nn.ReLU())
        self.expand1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.expand3 = nn.Sequential(
            nn.Conv2D(squeeze, e3, 3, padding=1), nn.ReLU())

    def forward(self, x):
        x = self.squeeze(x)
        return paddle.concat([self.expand1(x), self.expand3(x)], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, stride=2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2), _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
                nn.AdaptiveAvgPool2D(1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
            x = x.flatten(start_axis=1)
        return x


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet(version="1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet(version="1.1", **kwargs)
