"""DenseNet family (ref: `python/paddle/vision/models/densenet.py`).
NCHW; dense blocks concatenate features so XLA fuses the BN+ReLU+conv chains."""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn


class _DenseLayer(nn.Layer):
    def __init__(self, num_input_features, growth_rate, bn_size, drop_rate):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(num_input_features)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(num_input_features, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1,
                               bias_attr=False)
        self.drop_rate = drop_rate
        self.dropout = nn.Dropout(drop_rate) if drop_rate > 0 else None

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return paddle.concat([x, out], axis=1)


class _DenseBlock(nn.Layer):
    def __init__(self, num_layers, num_input_features, bn_size, growth_rate,
                 drop_rate):
        super().__init__()
        self.layers = nn.LayerList([
            _DenseLayer(num_input_features + i * growth_rate, growth_rate,
                        bn_size, drop_rate)
            for i in range(num_layers)
        ])

    def forward(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class _Transition(nn.Layer):
    def __init__(self, num_input_features, num_output_features):
        super().__init__()
        self.norm = nn.BatchNorm2D(num_input_features)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(num_input_features, num_output_features, 1,
                              bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


_CFG = {
    121: (6, 12, 24, 16),
    161: (6, 12, 36, 24),
    169: (6, 12, 32, 32),
    201: (6, 12, 48, 32),
    264: (6, 12, 64, 48),
}


class DenseNet(nn.Layer):
    """DenseNet (ref densenet.py:DenseNet)."""

    def __init__(self, layers=121, growth_rate=32, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        if layers == 161:
            growth_rate = 48
            num_init_features = 96
        else:
            num_init_features = 64
        block_config = _CFG[layers]
        self.conv0 = nn.Conv2D(3, num_init_features, 7, stride=2, padding=3,
                               bias_attr=False)
        self.norm0 = nn.BatchNorm2D(num_init_features)
        self.relu = nn.ReLU()
        self.pool0 = nn.MaxPool2D(3, stride=2, padding=1)

        blocks, feats = [], num_init_features
        for i, n in enumerate(block_config):
            blocks.append(_DenseBlock(n, feats, bn_size, growth_rate, dropout))
            feats += n * growth_rate
            if i != len(block_config) - 1:
                blocks.append(_Transition(feats, feats // 2))
                feats //= 2
        self.blocks = nn.LayerList(blocks)
        self.norm5 = nn.BatchNorm2D(feats)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(feats, num_classes)

    def forward(self, x):
        x = self.pool0(self.relu(self.norm0(self.conv0(x))))
        for b in self.blocks:
            x = b(x)
        x = self.relu(self.norm5(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = paddle.flatten(x, 1)
            x = self.classifier(x)
        return x


def _densenet(layers, **kwargs):
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, **kwargs)
