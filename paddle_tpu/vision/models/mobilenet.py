"""MobileNet V1/V2 (ref: `python/paddle/vision/models/mobilenetv1.py`,
`mobilenetv2.py`)."""
from __future__ import annotations

import paddle_tpu.nn as nn

__all__ = ["MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2"]


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, kernel=3, stride=1, groups=1, relu6=False):
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride,
                      padding=(kernel - 1) // 2, groups=groups,
                      bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.ReLU6() if relu6 else nn.ReLU())


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(int(ch * scale), 8)

        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_ConvBNReLU(3, c(32), stride=2)]
        for in_ch, out_ch, s in cfg:
            layers.append(_ConvBNReLU(c(in_ch), c(in_ch), stride=s,
                                      groups=c(in_ch)))    # depthwise
            layers.append(_ConvBNReLU(c(in_ch), c(out_ch), kernel=1))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(start_axis=1)
            x = self.fc(x)
        return x


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(in_c, hidden, kernel=1, relu6=True))
        layers.extend([
            _ConvBNReLU(hidden, hidden, stride=stride, groups=hidden,
                        relu6=True),
            nn.Conv2D(hidden, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c),
        ])
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        layers = [_ConvBNReLU(3, in_c, stride=2, relu6=True)]
        for t, ch, n, s in cfg:
            out_c = _make_divisible(ch * scale)
            for i in range(n):
                layers.append(_InvertedResidual(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        layers.append(_ConvBNReLU(in_c, last_c, kernel=1, relu6=True))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(start_axis=1)
            x = self.classifier(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
