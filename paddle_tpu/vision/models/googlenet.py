"""GoogLeNet / Inception v1 and Inception v3
(ref: `python/paddle/vision/models/googlenet.py`, `inceptionv3.py`)."""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class _ConvBN(nn.Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=padding, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _Inception(nn.Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _ConvBN(in_c, c1, 1)
        self.b2_1 = _ConvBN(in_c, c3r, 1)
        self.b2_2 = _ConvBN(c3r, c3, 3, padding=1)
        self.b3_1 = _ConvBN(in_c, c5r, 1)
        self.b3_2 = _ConvBN(c5r, c5, 5, padding=2)
        self.pool = nn.MaxPool2D(3, stride=1, padding=1)
        self.b4 = _ConvBN(in_c, proj, 1)

    def forward(self, x):
        return paddle.concat([
            self.b1(x),
            self.b2_2(self.b2_1(x)),
            self.b3_2(self.b3_1(x)),
            self.b4(self.pool(x)),
        ], axis=1)


class GoogLeNet(nn.Layer):
    """Inception v1 (ref googlenet.py:GoogLeNet). Returns (main, aux1, aux2)
    like the reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBN(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, padding=1),
            _ConvBN(64, 64, 1),
            _ConvBN(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(1024, num_classes)
            # aux classifiers (training heads, ref :aux_logits)
            self.aux1 = nn.Sequential(
                nn.AdaptiveAvgPool2D(4), nn.Flatten(),
                nn.Linear(512 * 16, 1024), nn.ReLU(), nn.Dropout(0.7),
                nn.Linear(1024, num_classes))
            self.aux2 = nn.Sequential(
                nn.AdaptiveAvgPool2D(4), nn.Flatten(),
                nn.Linear(528 * 16, 1024), nn.ReLU(), nn.Dropout(0.7),
                nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4a(x)
        aux1 = self.aux1(x) if self.num_classes > 0 else None
        x = self.i4d(self.i4c(self.i4b(x)))
        aux2 = self.aux2(x) if self.num_classes > 0 else None
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(paddle.flatten(x, 1)))
        return x, aux1, aux2


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)


# ------------------------------------------------------------- Inception v3

class _InceptionA(nn.Layer):
    def __init__(self, in_c, pool_features):
        super().__init__()
        self.b1 = _ConvBN(in_c, 64, 1)
        self.b5_1 = _ConvBN(in_c, 48, 1)
        self.b5_2 = _ConvBN(48, 64, 5, padding=2)
        self.b3_1 = _ConvBN(in_c, 64, 1)
        self.b3_2 = _ConvBN(64, 96, 3, padding=1)
        self.b3_3 = _ConvBN(96, 96, 3, padding=1)
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)
        self.bp = _ConvBN(in_c, pool_features, 1)

    def forward(self, x):
        return paddle.concat([
            self.b1(x), self.b5_2(self.b5_1(x)),
            self.b3_3(self.b3_2(self.b3_1(x))), self.bp(self.pool(x))], axis=1)


class _InceptionB(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _ConvBN(in_c, 384, 3, stride=2)
        self.bd_1 = _ConvBN(in_c, 64, 1)
        self.bd_2 = _ConvBN(64, 96, 3, padding=1)
        self.bd_3 = _ConvBN(96, 96, 3, stride=2)
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return paddle.concat([
            self.b3(x), self.bd_3(self.bd_2(self.bd_1(x))), self.pool(x)],
            axis=1)


class _InceptionC(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _ConvBN(in_c, 192, 1)
        self.b7_1 = _ConvBN(in_c, c7, 1)
        self.b7_2 = _ConvBN(c7, c7, (1, 7), padding=(0, 3))
        self.b7_3 = _ConvBN(c7, 192, (7, 1), padding=(3, 0))
        self.b77_1 = _ConvBN(in_c, c7, 1)
        self.b77_2 = _ConvBN(c7, c7, (7, 1), padding=(3, 0))
        self.b77_3 = _ConvBN(c7, c7, (1, 7), padding=(0, 3))
        self.b77_4 = _ConvBN(c7, c7, (7, 1), padding=(3, 0))
        self.b77_5 = _ConvBN(c7, 192, (1, 7), padding=(0, 3))
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)
        self.bp = _ConvBN(in_c, 192, 1)

    def forward(self, x):
        return paddle.concat([
            self.b1(x),
            self.b7_3(self.b7_2(self.b7_1(x))),
            self.b77_5(self.b77_4(self.b77_3(self.b77_2(self.b77_1(x))))),
            self.bp(self.pool(x))], axis=1)


class _InceptionD(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3_1 = _ConvBN(in_c, 192, 1)
        self.b3_2 = _ConvBN(192, 320, 3, stride=2)
        self.b7_1 = _ConvBN(in_c, 192, 1)
        self.b7_2 = _ConvBN(192, 192, (1, 7), padding=(0, 3))
        self.b7_3 = _ConvBN(192, 192, (7, 1), padding=(3, 0))
        self.b7_4 = _ConvBN(192, 192, 3, stride=2)
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return paddle.concat([
            self.b3_2(self.b3_1(x)),
            self.b7_4(self.b7_3(self.b7_2(self.b7_1(x)))),
            self.pool(x)], axis=1)


class _InceptionE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _ConvBN(in_c, 320, 1)
        self.b3_1 = _ConvBN(in_c, 384, 1)
        self.b3_2a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_2b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b33_1 = _ConvBN(in_c, 448, 1)
        self.b33_2 = _ConvBN(448, 384, 3, padding=1)
        self.b33_3a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b33_3b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)
        self.bp = _ConvBN(in_c, 192, 1)

    def forward(self, x):
        b3 = self.b3_1(x)
        b33 = self.b33_2(self.b33_1(x))
        return paddle.concat([
            self.b1(x),
            paddle.concat([self.b3_2a(b3), self.b3_2b(b3)], axis=1),
            paddle.concat([self.b33_3a(b33), self.b33_3b(b33)], axis=1),
            self.bp(self.pool(x))], axis=1)


class InceptionV3(nn.Layer):
    """Inception v3 (ref inceptionv3.py:InceptionV3)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBN(3, 32, 3, stride=2),
            _ConvBN(32, 32, 3),
            _ConvBN(32, 64, 3, padding=1),
            nn.MaxPool2D(3, stride=2),
            _ConvBN(64, 80, 1),
            _ConvBN(80, 192, 3),
            nn.MaxPool2D(3, stride=2),
        )
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160), _InceptionC(768, 160),
            _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048),
        )
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(paddle.flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)
