"""MobileNet v3 small/large (ref: `python/paddle/vision/models/mobilenetv3.py`)."""
from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _SE(nn.Layer):
    def __init__(self, c, r=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, _make_divisible(c // r), 1)
        self.fc2 = nn.Conv2D(_make_divisible(c // r), c, 1)

    def forward(self, x):
        s = self.pool(x)
        s = F.relu(self.fc1(s))
        s = F.hardsigmoid(self.fc2(s))
        return x * s


class _ConvBNAct(nn.Sequential):
    def __init__(self, in_c, out_c, k, stride=1, groups=1, act="hardswish"):
        layers = [
            nn.Conv2D(in_c, out_c, k, stride=stride, padding=k // 2,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_c),
        ]
        if act == "relu":
            layers.append(nn.ReLU())
        elif act == "hardswish":
            layers.append(nn.Hardswish())
        super().__init__(*layers)


class _Bneck(nn.Layer):
    def __init__(self, in_c, exp, out_c, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp != in_c:
            layers.append(_ConvBNAct(in_c, exp, 1, act=act))
        layers.append(_ConvBNAct(exp, exp, k, stride=stride, groups=exp, act=act))
        if use_se:
            layers.append(_SE(exp))
        layers.append(_ConvBNAct(exp, out_c, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_LARGE = [
    # k, exp, out, se, act, stride
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]

_SMALL = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class MobileNetV3(nn.Layer):
    def __init__(self, config, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        self.conv0 = _ConvBNAct(3, in_c, 3, stride=2, act="hardswish")
        blocks = []
        for k, exp, out_c, se, act, stride in config:
            exp_c = _make_divisible(exp * scale)
            out_sc = _make_divisible(out_c * scale)
            blocks.append(_Bneck(in_c, exp_c, out_sc, k, stride, se, act))
            in_c = out_sc
        self.blocks = nn.Sequential(*blocks)
        last_conv = _make_divisible(6 * in_c)
        self.conv_last = _ConvBNAct(in_c, last_conv, 1, act="hardswish")
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.conv_last(self.blocks(self.conv0(x)))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(paddle.flatten(x, 1))
        return x


class MobileNetV3Large(MobileNetV3):
    """MobileNetV3-Large (ref mobilenetv3.py:MobileNetV3Large)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 1280, scale=scale, num_classes=num_classes,
                         with_pool=with_pool)


class MobileNetV3Small(MobileNetV3):
    """MobileNetV3-Small (ref mobilenetv3.py:MobileNetV3Small)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 1024, scale=scale, num_classes=num_classes,
                         with_pool=with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)
