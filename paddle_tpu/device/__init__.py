"""Device management (ref: `python/paddle/device/__init__.py`, `phi/common/place.h`).

On TPU there is one device kind per process topology; places map onto jax devices.
"""
from __future__ import annotations

import jax


class Place:
    def __init__(self, kind: str, device_id: int = 0):
        self._kind = kind
        self._id = device_id

    def __repr__(self):
        return f"Place({self._kind}:{self._id})"

    def __eq__(self, other):
        return isinstance(other, Place) and (self._kind, self._id) == \
            (other._kind, other._id)

    def __hash__(self):
        return hash((self._kind, self._id))

    def is_tpu_place(self):
        return self._kind == "tpu"

    def is_cpu_place(self):
        return self._kind == "cpu"

    def is_gpu_place(self):
        return False


def CPUPlace():
    return Place("cpu", 0)


def TPUPlace(device_id=0):
    return Place("tpu", device_id)


CUDAPlace = TPUPlace  # scripts written for the reference keep working on TPU
CUDAPinnedPlace = CPUPlace
XPUPlace = TPUPlace

_current_device = None


def _backend_kind() -> str:
    plat = jax.default_backend()
    return "cpu" if plat == "cpu" else "tpu"


def set_device(device):
    """ref: ``paddle.device.set_device`` — accepts 'cpu', 'tpu', 'tpu:0', and for
    script compatibility 'gpu'/'gpu:0' (routed to the TPU backend)."""
    global _current_device
    dev = str(device)
    _current_device = dev
    return get_device()


def get_device() -> str:
    if _current_device is not None:
        return _current_device
    kind = _backend_kind()
    return f"{kind}:0" if kind != "cpu" else "cpu"


def get_all_custom_device_type():
    return ["tpu"] if _backend_kind() == "tpu" else []


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_custom_device(device_type="tpu"):
    return device_type == "tpu"


def is_compiled_with_distribute():
    return True


def device_count():
    return jax.device_count()


def _place_of(arr) -> Place:
    try:
        devs = arr.devices()
        d = next(iter(devs))
        kind = "cpu" if d.platform == "cpu" else "tpu"
        return Place(kind, d.id)
    except Exception:
        return Place(_backend_kind(), 0)


def synchronize(device=None):
    """Block until all queued device work finishes (ref: paddle.device.synchronize)."""
    import jax.numpy as jnp
    jnp.zeros(()).block_until_ready()


class Stream:
    """XLA has no user-visible streams; kept for API parity (no-op)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_stream(self, stream):
        pass

    def wait_event(self, event):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None):
    return Stream(device)


def stream_guard(stream):
    import contextlib
    return contextlib.nullcontext()


# ------------------------------------------------------- memory introspection
# (ref `paddle.device.cuda.max_memory_allocated` etc., `memory/stats.cc`;
# on TPU the numbers come from the PJRT device's memory_stats)


def _mem_stats(device=None):
    import jax
    d = jax.local_devices()[0] if device is None else device
    try:
        return d.memory_stats() or {}
    except Exception:
        return {}


def max_memory_allocated(device=None):
    """Peak bytes in use on the device (ref device/cuda:max_memory_allocated)."""
    return int(_mem_stats(device).get("peak_bytes_in_use", 0))


def memory_allocated(device=None):
    """Current bytes in use (ref device/cuda:memory_allocated)."""
    return int(_mem_stats(device).get("bytes_in_use", 0))


def max_memory_reserved(device=None):
    """Peak bytes reserved by the allocator pool (ref max_memory_reserved)."""
    s = _mem_stats(device)
    return int(s.get("peak_pool_bytes", s.get("peak_bytes_in_use", 0)))


def memory_reserved(device=None):
    s = _mem_stats(device)
    return int(s.get("pool_bytes", s.get("bytes_in_use", 0)))


class cuda:
    """Namespace shim: `paddle.device.cuda.*` memory queries report the
    accelerator (TPU) allocator stats so profiling code ports unchanged."""

    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_allocated = staticmethod(memory_allocated)
    max_memory_reserved = staticmethod(max_memory_reserved)
    memory_reserved = staticmethod(memory_reserved)

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        return synchronize(device)

    @staticmethod
    def empty_cache():
        import gc
        gc.collect()
