"""Multi-replica serving: the subsystem ABOVE one engine process.

`inference/engine.py` + `inference/serve.py` end at one process on one
mesh; this package is the scale-out story (ROADMAP item 1, "millions of
users"): a router front door that load-balances GENERATE requests across N
engine replicas discovered through the elastic registry
(`distributed/fleet/elastic.py`), with pluggable placement policies and
bounded resubmission around replica failures. The multi-program
coordination shape follows the MPMD pipeline-parallelism paper
(arxiv 2412.14374) — Python owns placement and membership, every replica
keeps its own fixed-shape device programs — and replica-level scale-out is
the serving comparison's production path (arxiv 2605.25645).

Run a fleet (docs/SERVING.md "Scaling out"):

    # replicas register themselves
    python -m paddle_tpu.inference.serve --gpt-config g.json \
        --registry-dir /mnt/registry --replica-id r0
    # the router watches the registry and fronts them
    python -m paddle_tpu.serving.router --registry-dir /mnt/registry

Clients speak the unchanged serve wire protocol to the router
(`RemotePredictor` works as-is); the router forwards GENERATE to a replica
picked by policy, resubmits on replica failure, and serves its own
STATS/PROMETHEUS from the local metrics registry.

The control plane itself is redundant (docs/ROBUSTNESS.md
"Control-plane HA"): run N routers over the same registry — each routes
independently (soft state, no leader), registers under the distinct
``router`` role (``--router-id``), and clients
(`RemotePredictor(endpoints=...)` or ``registry_dir=``) fail over across
them mid-request with exactly-once semantics via per-request idempotency
keys and each engine's dedup table.

`autoscale.py` closes the elasticity loop (ROADMAP item 2): a controller
that watches per-replica STATS + the router's outstanding view and
spawns/drains replicas between ``min_replicas`` and ``max_replicas`` —
scale-down drains WITH live request migration (`InferenceServer.drain
(migrate_peers=...)`, docs/SERVING.md "Live migration"), so shrinking the
fleet or losing a preemptible VM costs zero client-visible errors.
"""
from paddle_tpu.serving.autoscale import (Autoscaler, AutoscalePolicy,
                                          CallbackLauncher)
from paddle_tpu.serving.disagg import (KVStreamAssembler, PrefixDirectory,
                                       prompt_page_hashes, stream_records)
from paddle_tpu.serving.router import POLICIES, ReplicaState, Router

__all__ = ["Router", "ReplicaState", "POLICIES", "Autoscaler",
           "AutoscalePolicy", "CallbackLauncher", "KVStreamAssembler",
           "PrefixDirectory", "prompt_page_hashes", "stream_records"]
