"""Router: a wire-compatible front door over N engine replicas.

One `DecodeEngine` process serves one mesh; production traffic needs a
fleet. The router speaks the EXISTING serve wire protocol
(`inference/serve.py` — hello auth, ops GENERATE/STATS/PROMETHEUS/PING/
SHUTDOWN), so every client that talks to one replica talks to the router
unchanged. Behind the front door:

- **Membership** comes from the elastic registry
  (`distributed/fleet/elastic.py`): replicas register
  ``node_<id>.json``-style leases (file or TCP backend) and renew them on
  heartbeats; the router polls ``alive_nodes()`` in observer mode — a
  replica that joins mid-stream starts receiving traffic on the next poll,
  a replica whose heartbeat expires is routed around. Static fleets (tests,
  bench) pass ``replicas={id: "host:port"}`` instead.
- **Placement policies** (``POLICIES``): ``round_robin`` (default),
  ``least_outstanding`` (fewest router-tracked in-flight requests), and
  ``slo_aware`` — the poll thread pulls each replica's metrics snapshot
  over the STATS op and ranks replicas by their ``serve.tpot_seconds`` p99
  (the decode SLO the tracing layer maintains), outstanding count as the
  tiebreak; replicas with no observations yet rank optimistically so fresh
  capacity warms up.
- **Failure handling — a circuit breaker per replica**
  (docs/ROBUSTNESS.md): every replica carries a breaker with the classic
  three states. CLOSED = in rotation; background PING health probes run
  each poll cycle, and ``breaker_threshold`` consecutive probe failures —
  or ONE request-path connection failure / not-taking-work answer — OPEN
  it (out of rotation, the old "eviction"). After ``evict_cooldown_s`` an
  open breaker goes HALF-OPEN: the next health probe (or a trial request,
  when no closed replica remains) decides — success re-closes it, failure
  re-opens with a fresh cooldown. The failed request itself is resubmitted
  to another replica under a bounded budget (``max_resubmits``) — a
  mid-flight replica kill is a retry, not a client-visible error.
  Application errors (bad request, ``DeadlineExceeded``, ``Cancelled``)
  relay to the client unchanged and are never resubmitted; a typed
  ``Overloaded`` answer resubmits WITHOUT opening the breaker (the
  replica is healthy, just full) — and when every replica sheds, the
  client gets one clean typed ``Overloaded`` line, never a hang.
- **Deadline budget forwarding**: a GENERATE whose options array carries
  ``deadline_ms`` is forwarded with the REMAINING budget on every
  (re)submit, and the per-attempt IO timeout is clipped to it — the
  client's deadline bounds the whole routed attempt chain, resubmits
  included (``router.deadline_exceeded`` counts budget exhaustion).
- **Redundant routers** (docs/ROBUSTNESS.md "Control-plane HA"): N
  routers run simultaneously over the shared registry, each routing
  independently — routing state is SOFT (breakers/outstanding rebuild
  from probes), so there is no leader. A router registers ITSELF under
  the distinct ``router`` role (``--router-id`` -> node id
  ``router:<id>``) for client discovery; router-role leases never enter
  any replica rotation. Requests carrying an idempotency KEY route by
  rendezvous hash — routers with the same healthy view independently
  pick the same replica, so a failover resubmit lands on the engine
  whose dedup table already owns the key (best-effort while breaker
  views transiently diverge; the dedup table bounds duplicates to that
  window) — and an ambiguous mid-wire death gets one same-replica retry
  (``router.ack_retries``) instead of an eviction.

Observability (docs/OBSERVABILITY.md): ``router.requests``,
``router.replica_errors``, ``router.resubmits``, ``router.no_replica``,
per-replica ``router.replica_requests{replica=..}`` counters and
``router.outstanding{replica=..}`` gauges, a ``router.request_seconds``
histogram, and a ``router.forward`` span per routed request tagged with
the replica id — one Perfetto filter shows which replica served a request.

- **Disaggregated serving** (docs/SERVING.md "Disaggregated serving"):
  replicas declare a tier via their lease role (``prefill:<id>`` /
  ``decode:<id>``; unprefixed = legacy symmetric). With both tiers
  healthy the router drives GENERATE two-phase: OP_PREFILL to a prefill
  worker picked with CACHE AFFINITY — a fleet-wide prefix directory
  (`serving/disagg.py` PrefixDirectory, keyed by the engines' rolling
  page hashes, fed from their STATS prefix exports and the router's own
  routing, invalidated on eviction/refresh/membership churn) biases
  shared-prefix traffic to the worker already holding the longest
  prefix, so a system prompt is prefilled once per FLEET — and the
  worker's PTKS1 page records relay to the decode replica's OP_KV_STREAM
  as they are produced. The decode replica admits the slot when the
  final record lands and answers the full sequence, token-identical to a
  symmetric route; it never compiles a prefill program. Deadlines,
  cancel tags and idempotency keys ride the stream options; a prefill
  worker dying mid-stream falls back to one symmetric attempt
  (``router.disagg_fallbacks``) with the partial pages discarded
  cleanly.

The router is deliberately stateless about request CONTENT: GENERATE in,
int32 ids out (the disaggregated flow relays opaque checksummed page
records — it still never interprets them).
"""
from __future__ import annotations

import argparse
import hashlib
import os
import secrets as _secrets
import socket
import struct
import threading
import time

import numpy as np

from paddle_tpu.distributed.fleet.elastic import node_role, router_node_id
from paddle_tpu.inference.errors import DeadlineExceeded, Overloaded
from paddle_tpu.inference.serve import (MAGIC, OP_CANCEL, OP_DEBUG_DUMP,
                                        OP_GENERATE, OP_KV_STREAM, OP_PING,
                                        OP_PREFILL, OP_PROMETHEUS, OP_RUN,
                                        OP_SHUTDOWN, OP_STATS,
                                        OP_TRACE_EXPORT, _recv_exact,
                                        auth_token, debug_dump_payload,
                                        recv_arrays, retrying_connect,
                                        send_arrays, stats_payload,
                                        trace_export_payload)
from paddle_tpu.serving.disagg import PrefixDirectory, prompt_page_hashes
from paddle_tpu.observability import metrics
from paddle_tpu.observability.flight_recorder import flight
from paddle_tpu.observability.tracing import (new_request_id, new_span_id,
                                              trace_to_words, words_to_trace)
from paddle_tpu.testing import faults

__all__ = ["Router", "ReplicaState", "POLICIES", "ReplicaUnavailable"]


class ReplicaUnavailable(ConnectionError):
    """The replica answered, but with a not-taking-work error (draining,
    engine stopped) — resubmit elsewhere, same as a dead connection."""


class _ReplicaAppError(RuntimeError):
    """The replica rejected the REQUEST itself: relaying it to another
    replica would fail identically, so it goes straight back to the
    client and never burns resubmit budget."""


class _ClientDisconnected(RuntimeError):
    """The ROUTER's client hung up mid-GENERATE. Deliberately NOT a
    ConnectionError/OSError: it must escape the resubmit loop (nobody is
    left to answer) instead of burning budget on another replica."""


def _classify_wire_error(msg: str) -> Exception:
    """Split replica wire errors by the exception TYPE the replica raised
    (the wire message is ``<Type>: <text>``): a ``ValueError`` is request
    validation (bad prompt/length — identical on every replica, relay it),
    as is an engine-less replica serving only RUN; ``DeadlineExceeded``
    and ``Cancelled`` are terminal per-request outcomes — the deadline is
    global and the cancel was the client's own, so another replica changes
    neither: relay them verbatim. Everything else — draining, engine
    stopped/aborted/died, result timeout, a typed ``Overloaded`` shed —
    means THIS replica can't finish the work, which is exactly what
    resubmission is for. Defaulting to resubmittable is deliberate: abort
    reasons are free-form text, and a missed marker must cost a bounded
    retry, not a client-visible error."""
    if msg.startswith(("ValueError", "DeadlineExceeded", "Cancelled")) \
            or "no decode engine attached" in msg:
        return _ReplicaAppError(msg)
    return ReplicaUnavailable(msg)


# a replica-answered error justifies EVICTION (not just resubmission of
# this one request) only when it says the replica stopped taking work;
# other request-scoped failures ("request needs N pages", result timeout)
# must not let one bad request empty the whole rotation for a cooldown
_EVICT_MARKERS = ("drain", "engine stopped", "engine loop died")


def _should_evict(e: Exception) -> bool:
    """Connection-level failures (refused/dropped/timed-out sockets) always
    evict — the replica's wire stack is gone. A `ReplicaUnavailable` the
    replica ANSWERED with evicts only on an explicit not-taking-work
    marker; anything else resubmits this request (the `tried` set already
    keeps it off the same replica) while the replica stays in rotation
    for everyone else."""
    if not isinstance(e, ReplicaUnavailable):
        return True
    return any(m in str(e) for m in _EVICT_MARKERS)


class ReplicaState:
    """Router-side view of one engine replica, including its circuit
    breaker: ``closed`` (in rotation) -> ``open`` (out of rotation —
    request-path eviction or ``breaker_threshold`` consecutive probe
    failures) -> after the cooldown ``half_open`` (one probe/trial
    decides) -> ``closed`` again or back to ``open``
    (docs/ROBUSTNESS.md "Circuit breaker")."""

    __slots__ = ("replica_id", "endpoint", "outstanding", "errors",
                 "breaker", "consec_fail", "probe_at", "evicted_at",
                 "stats", "stats_at", "role", "_g_out")

    def __init__(self, replica_id: str, endpoint: str):
        self.replica_id = replica_id
        self.endpoint = endpoint
        # disaggregation tier (docs/SERVING.md "Disaggregated serving"):
        # parsed from the lease id's role prefix ('prefill:'/'decode:');
        # an unprefixed legacy id is the symmetric 'both' tier. The
        # replica's own STATS role export refines this (static fleets
        # whose ids carry no prefix still classify).
        role = node_role(replica_id)
        self.role = role if role in ("prefill", "decode") else "both"
        self.outstanding = 0
        self.errors = 0
        self.breaker = "closed"
        self.consec_fail = 0       # consecutive health-probe failures
        self.probe_at = 0.0        # last health probe (monotonic)
        self.evicted_at = 0.0      # breaker-open timestamp (monotonic)
        self.stats = None          # last STATS snapshot (slo_aware policy)
        self.stats_at = 0.0
        self._g_out = metrics.gauge("router.outstanding",
                                    replica=replica_id)

    @property
    def draining(self) -> bool:
        """Back-compat view: out of normal rotation (breaker not
        closed)."""
        return self.breaker != "closed"


def _pick_round_robin(router: "Router", cands: list[ReplicaState]):
    router._rr += 1
    return cands[router._rr % len(cands)]


def _pick_least_outstanding(router: "Router", cands: list[ReplicaState]):
    return min(cands, key=lambda r: (r.outstanding, r.replica_id))


def _pick_slo_aware(router: "Router", cands: list[ReplicaState]):
    """Best observed decode SLO wins: rank by the replica's own
    ``serve.tpot_seconds`` p99 (pulled over STATS by the poll thread),
    outstanding as the tiebreak. A replica with no observations yet scores
    0.0 — optimistic, so fresh capacity gets traffic and earns a score."""
    def score(r: ReplicaState):
        tpot = None
        if r.stats:
            h = r.stats.get("histograms", {}).get("serve.tpot_seconds")
            if h:
                tpot = h.get("p99")
        return (0.0 if tpot is None else float(tpot), r.outstanding,
                r.replica_id)
    return min(cands, key=score)


POLICIES = {
    "round_robin": _pick_round_robin,
    "least_outstanding": _pick_least_outstanding,
    "slo_aware": _pick_slo_aware,
}


class Router:
    """Front door process: accepts serve-protocol connections, forwards
    GENERATE to a policy-picked replica, resubmits around failures.

    >>> router = Router(replicas={"r0": f"127.0.0.1:{p0}",
    ...                           "r1": f"127.0.0.1:{p1}"},
    ...                 replica_secret="fleet", auth_name="front")
    >>> threading.Thread(target=router.serve_forever, daemon=True).start()
    >>> cli = RemotePredictor(port=router.port, secret="front")
    >>> out = cli.generate(prompt_ids, max_new_tokens=64)

    ``registry`` is an observer-mode NodeRegistry / TcpNodeRegistry whose
    ``alive_nodes()`` maps replica id -> "host:port"; ``replicas`` is the
    static equivalent (both compose — static entries survive registry
    churn). ``replica_secret`` is the fleet-shared auth secret every
    replica was started with (its ``--auth-name``); None falls back to
    ``PADDLE_SERVE_TOKEN`` on both sides. The router's OWN client-facing
    auth follows the serve rules: ``auth_name`` > ``PADDLE_SERVE_TOKEN`` >
    a random per-startup token in ``generated_secret``.
    """

    def __init__(self, registry=None, replicas=None, policy="round_robin",
                 host="127.0.0.1", port=0, auth_name=None,
                 replica_secret=None, poll_interval_s=1.0,
                 stats_interval_s=5.0, max_resubmits=2,
                 evict_cooldown_s=5.0, connect_deadline_s=5.0,
                 request_timeout_s=600.0, breaker_threshold=3,
                 health_interval_s=None, page_size=None,
                 directory_capacity=4096):
        if policy not in POLICIES:
            raise ValueError(
                f"unknown policy {policy!r}; have {sorted(POLICIES)}")
        if registry is None and not replicas:
            raise ValueError("need a registry and/or static replicas")
        self._registry = registry
        self._policy = policy
        self._poll_interval = float(poll_interval_s)
        self._stats_interval = float(stats_interval_s)
        self._max_resubmits = int(max_resubmits)
        self._evict_cooldown = float(evict_cooldown_s)
        self._connect_deadline = float(connect_deadline_s)
        self._request_timeout = float(request_timeout_s)
        self._breaker_threshold = max(1, int(breaker_threshold))
        # PING probe cadence per replica; defaults to the poll interval
        # (probes ride the poll thread's cycle)
        self._health_interval = float(poll_interval_s
                                      if health_interval_s is None
                                      else health_interval_s)
        self._replica_token = auth_token(
            None if replica_secret is None else str(replica_secret))
        # fleet prefix directory (docs/SERVING.md "Disaggregated
        # serving"): rolling page hash -> prefill replica, fed by the
        # replicas' STATS prefix exports and the router's own routing;
        # `page_size` keys the prompt hashing — None learns it from the
        # first engine STATS pull (affinity is policy-pick until then)
        self._directory = PrefixDirectory(capacity=directory_capacity)
        self._page_size = None if page_size is None else int(page_size)
        self._rr = -1
        self._rlock = threading.Lock()
        self._replicas: dict[str, ReplicaState] = {}
        self._static = dict(replicas or {})
        # fold the registry in SYNCHRONOUSLY before listening: a
        # registry-only router must not serve its first poll_interval of
        # requests with an empty rotation
        reg_view = {}
        if registry is not None:
            try:
                reg_view = registry.alive_nodes()
            except OSError:
                pass               # registry not up yet: the poll catches up
        self._sync_membership(reg_view)

        self.generated_secret = None
        if auth_name is not None:
            basis = auth_name
        elif os.environ.get("PADDLE_SERVE_TOKEN"):
            basis = None
        else:
            self.generated_secret = _secrets.token_hex(16)
            basis = self.generated_secret
        self._token = auth_token(basis if basis is None else str(basis))

        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._lease = None            # router-role registry lease
        self._fleet = None            # FleetMetrics fed by _refresh_stats
        self._slo = None              # fleet-scope SLOEvaluator (attach_slo)
        self._conns: set[socket.socket] = set()   # live client conns
        self._conn_lock = threading.Lock()
        # the membership poll thread ALWAYS runs: beyond registry
        # membership it is what re-admits an error-evicted replica after
        # the cooldown (static fleets included — without it an eviction
        # would be permanent). slo_aware's STATS pulls live on their OWN
        # thread: a half-open replica blocking a stats read must never
        # stall membership sync
        self._poll_thread = threading.Thread(
            target=self._poll_loop, daemon=True, name="pt-router-poll")
        self._poll_thread.start()
        # PING health probes get their OWN thread (docs/ROBUSTNESS.md):
        # probe IO against a dead replica must never stall membership
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True, name="pt-router-health")
        self._probe_thread.start()
        # the STATS thread ALWAYS runs now (it used to be slo_aware-only):
        # beyond SLO ranking it is the fleet prefix directory's data feed
        # and how a static replica's role/page_size are learned — a
        # disaggregated fleet without it would never build affinity
        self._stats_thread = threading.Thread(
            target=self._stats_loop, daemon=True, name="pt-router-stats")
        self._stats_thread.start()

    # ----------------------------------------------------------- membership

    def replica_ids(self, healthy_only=False) -> list[str]:
        with self._rlock:
            return sorted(r.replica_id for r in self._replicas.values()
                          if not (healthy_only and r.draining))

    def replica_view(self) -> list[dict]:
        """Point-in-time snapshot of the rotation — one dict per replica
        with ``replica_id``/``endpoint``/``outstanding``/``breaker`` —
        for controllers that observe the router without reaching into its
        locking (the autoscaler, `serving/autoscale.py`)."""
        with self._rlock:
            return [dict(replica_id=r.replica_id, endpoint=r.endpoint,
                         outstanding=r.outstanding, breaker=r.breaker,
                         role=r.role)
                    for r in sorted(self._replicas.values(),
                                    key=lambda x: x.replica_id)]

    def _sync_membership(self, registry_alive: dict):
        """Fold one REGISTRY view in: new ids join rotation (breaker
        closed), missing ids (lease expired or deregistered) leave it.
        The static set is read HERE, under `_rlock` — never from a
        caller's snapshot — so a replica `remove_static_replica` just
        dropped cannot be resurrected (and a freshly added one cannot be
        transiently evicted) by a poll cycle that raced the mutation; a
        registry lease for the SAME id still wins the endpoint (a
        self-registering replica that restarts on a new port must be
        followed, not pinned to its stale static entry).
        An OPEN breaker is NOT reset by the registry still vouching for
        the replica — a crashed process keeps a fresh lease until its
        TTL; re-admission is the health probe's job (open -> half_open
        after the cooldown, then a successful PING closes it).
        ROUTER-role leases (``router:<id>`` — this router's own siblings
        in a redundant control plane) are NOT replicas: they share the
        registry for client discovery and never enter the rotation."""
        with self._rlock:
            alive = dict(self._static)
            # every non-router role joins the rotation — legacy replicas
            # ('both'), prefill workers and decode replicas alike; the
            # tier decides WHICH traffic they get (`_pick` keeps pure
            # prefill workers out of GENERATE placement)
            alive.update({rid: ep for rid, ep in registry_alive.items()
                          if node_role(rid) != "router"})
            for rid, ep in alive.items():
                self._join_replica(rid, str(ep))
            for rid in [rid for rid in self._replicas if rid not in alive]:
                self._leave_replica(self._replicas.pop(rid))

    def _join_replica(self, rid: str, ep: str):
        """Fold one replica into the rotation (or follow its endpoint) —
        the ONE join bookkeeping path, shared by the membership poll and
        `add_static_replica`. Caller holds ``_rlock``."""
        r = self._replicas.get(rid)
        if r is None:
            self._replicas[rid] = ReplicaState(rid, ep)
            metrics.counter("router.replica_joins").inc()
            flight.record("router.join", replica=rid, endpoint=ep)
        else:
            r.endpoint = ep

    def _leave_replica(self, r):
        """Leave bookkeeping for a replica already popped from the
        rotation — the ONE leave path, shared by the membership poll and
        `remove_static_replica`. Membership churn invalidates the
        replica's fleet-directory entries: affinity must never bias a
        route toward a corpse."""
        r._g_out.set(0)
        self._directory.invalidate(r.replica_id)
        metrics.counter("router.replica_leaves").inc()
        flight.record("router.leave", replica=r.replica_id)

    def add_static_replica(self, replica_id: str, endpoint: str):
        """Fold one replica into the STATIC membership at runtime (the
        autoscaler's spawn path, `serving/autoscale.py`): it joins the
        rotation immediately and survives registry churn like any other
        static entry. Thread-safe; re-adding an existing id just updates
        its endpoint. The `_static` mutation happens under `_rlock` —
        `_sync_membership` reads `_static` under the same lock, so a poll
        cycle can never observe (and act on) a half-applied change."""
        rid, ep = str(replica_id), str(endpoint)
        with self._rlock:
            self._static[rid] = ep
            self._join_replica(rid, ep)

    def remove_static_replica(self, replica_id: str):
        """Drop a replica from the static set AND the live rotation (the
        autoscaler's scale-down path — called BEFORE the drain so no new
        traffic lands on the victim while it migrates its in-flight work
        away). Atomic with respect to the membership poll (same `_rlock`
        discipline as `add_static_replica` — a concurrent `_sync_membership`
        can never resurrect the victim from a stale static snapshot). A
        registry lease for the same id re-admits it on the next poll;
        static scale-down therefore uses launcher-owned ids that never
        carry a lease."""
        rid = str(replica_id)
        with self._rlock:
            self._static.pop(rid, None)
            r = self._replicas.pop(rid, None)
        if r is not None:
            self._leave_replica(r)

    def _poll_loop(self):
        while not self._stop.wait(self._poll_interval):
            reg_view = {}
            if self._registry is not None:
                try:
                    reg_view = self._registry.alive_nodes()
                except OSError:
                    continue       # transient registry outage: hold steady
            self._sync_membership(reg_view)

    # ------------------------------------------------------ circuit breaker

    def _probe_loop(self):
        # probes live on their OWN thread: an unreachable replica's probe
        # IO (up to the probe deadline each) must never stall membership
        # sync or delay the other replicas' breaker transitions. The loop
        # survives ANY probe exception — open->half_open->closed recovery
        # happens nowhere else, so a dead probe thread would turn every
        # future breaker-open into a permanent eviction
        while not self._stop.wait(self._health_interval):
            try:
                self._probe_replicas()
            except Exception:  # noqa: BLE001 — recovery must outlive bugs
                metrics.counter("router.probe_errors").inc()

    def _probe_replicas(self):
        """Background PING health probes (one per replica per
        ``health_interval_s``, on the dedicated health thread): a closed
        replica failing ``breaker_threshold`` consecutive probes opens
        its breaker BEFORE a client request has to discover the corpse;
        an open breaker past the cooldown goes half-open and the probe's
        verdict closes or re-opens it."""
        now = time.monotonic()
        due = []
        with self._rlock:
            for r in self._replicas.values():
                if r.breaker == "open" and \
                        now - r.evicted_at >= self._evict_cooldown:
                    r.breaker = "half_open"
                    metrics.counter("router.breaker_half_open").inc()
                    flight.record("router.breaker", replica=r.replica_id,
                                  state="half_open")
                if r.breaker == "half_open" or (
                        r.breaker == "closed"
                        and now - r.probe_at >= self._health_interval):
                    due.append(r)
        # concurrent fan-out (same pattern as _route_cancel): one dead
        # replica's probe must cost the CYCLE its own deadline, not push
        # every later replica's probe and breaker transition behind it
        def _one(rep):
            rep.probe_at = time.monotonic()
            self._record_probe(rep, self._ping_replica(rep))
        ths = [threading.Thread(target=_one, args=(rep,), daemon=True)
               for rep in due]
        for t in ths:
            t.start()
        for t in ths:
            t.join()

    def _ping_replica(self, r: ReplicaState) -> bool:
        """One authed PING exchange at probe-grade timeouts (clipped to
        2 s regardless of the request-path connect deadline) — a probe
        must cost this loop milliseconds-to-seconds, never a request
        timeout."""
        probe_deadline = min(self._connect_deadline, 2.0)
        try:
            # endpoint parse INSIDE the guard: a malformed registry entry
            # ("host" with no port) is a failed probe, not a probe-thread
            # killer
            host, port = r.endpoint.rsplit(":", 1)
            sock = retrying_connect(host, int(port),
                                    timeout=probe_deadline + 2.0,
                                    attempts=1,
                                    deadline_s=probe_deadline)
        except (OSError, ConnectionError, ValueError):
            return False
        try:
            sock.sendall(struct.pack("<I", MAGIC) + self._replica_token)
            sock.sendall(struct.pack("<III", MAGIC, OP_PING, 0))
            magic, status, _ = struct.unpack(
                "<III", _recv_exact(sock, 12))
            return magic == MAGIC and status == 0
        except (OSError, ConnectionError, struct.error):
            return False
        finally:
            sock.close()

    def _record_probe(self, r: ReplicaState, ok: bool):
        with self._rlock:
            if ok:
                r.consec_fail = 0
                # a successful probe closes HALF-OPEN only: a stale PING
                # that was in flight when the request path opened the
                # breaker must not re-close it with no cooldown (PING
                # succeeding is weak evidence — a dead engine's serve
                # loop still answers it); an open breaker waits out its
                # cooldown and earns closure from the half-open probe
                if r.breaker == "half_open":
                    r.breaker = "closed"
                    metrics.counter("router.breaker_close").inc()
                    flight.record("router.breaker",
                                  replica=r.replica_id, state="closed")
                return
            r.consec_fail += 1
            if r.breaker == "half_open" or (
                    r.breaker == "closed"
                    and r.consec_fail >= self._breaker_threshold):
                self._open_breaker_locked(r, "health probe failed")

    def _open_breaker_locked(self, r: ReplicaState, reason: str):
        """Caller holds ``_rlock``."""
        r.breaker = "open"
        r.evicted_at = time.monotonic()
        r.errors += 1
        metrics.counter("router.breaker_open").inc()
        flight.record("router.breaker", replica=r.replica_id,
                      state="open", reason=reason)

    def _stats_loop(self):
        while not self._stop.wait(self._poll_interval):
            self._refresh_stats()
            if self._slo is not None and self._fleet is not None:
                # fleet-scope burn-rate pass over the rollup the SAME
                # pull just refreshed — alert evaluation rides the
                # existing cadence, no second clock
                try:
                    self._slo.evaluate(self._fleet.rollup())
                except Exception:  # noqa: BLE001 — telemetry never
                    pass           # stalls the stats loop

    def _refresh_stats(self):
        """Pull each healthy replica's STATS snapshot (rate-limited per
        replica) so `slo_aware` ranks on fresh serve.tpot histograms. A
        failed pull only ages the cached stats — placement failure
        handling stays with the forward path."""
        now = time.monotonic()
        with self._rlock:
            due = [r for r in self._replicas.values()
                   if not r.draining
                   and now - r.stats_at >= self._stats_interval]
        for r in due:
            # stats_at advances on FAILURE too: a wedged replica must be
            # rate-limited like a healthy one, or it would stay "due" and
            # stall every poll cycle back to back
            r.stats_at = time.monotonic()
            try:
                # short dedicated IO timeout: a STATS pull is a few KB of
                # telemetry, never worth the full GENERATE request
                # timeout — a half-open replica must cost this loop
                # seconds, not minutes
                snap = self._replica_op(r, OP_STATS,
                                        timeout=self._connect_deadline + 5.0)
                import json
                r.stats = json.loads(snap.tobytes().decode())
            except (OSError, ConnectionError, ValueError):
                continue
            if self._fleet is not None:
                # fleet metrics plane (docs/OBSERVABILITY.md "Fleet
                # metrics plane"): the SAME pull that feeds slo_aware and
                # the prefix directory feeds the fleet rollup — no second
                # scrape loop against the replicas
                try:
                    self._fleet.ingest(r.replica_id, r.role, r.endpoint,
                                       r.stats)
                except (TypeError, ValueError, KeyError):
                    pass    # malformed snapshot: the rollup keeps its view
            # disaggregation extras (docs/SERVING.md "Disaggregated
            # serving"): the replica's self-declared role (refines the
            # lease-prefix classification — static fleets with
            # unprefixed ids still tier), the fleet page size, and —
            # for prefill workers — the prefix-store hashes that FEED
            # the fleet directory (replace() also drops entries the
            # store evicted or flushed: stale affinity self-heals)
            role = r.stats.get("role")
            if role in ("both", "prefill", "decode"):
                r.role = role
            pre = r.stats.get("prefix") or {}
            if self._page_size is None and pre.get("page_size"):
                self._page_size = int(pre["page_size"])
            if r.role == "prefill" and ("hashes" in pre
                                        or "spilled" in pre):
                try:
                    # KV tiering: SPILLED chains route like resident ones
                    # (the replica re-uploads on hit — docs/SERVING.md
                    # "KV tiering"); the directory just meters them apart
                    spilled = [bytes.fromhex(h)
                               for h in pre.get("spilled", [])]
                    self._directory.replace(
                        r.replica_id,
                        [bytes.fromhex(h)
                         for h in pre.get("hashes", [])] + spilled,
                        spilled=spilled)
                except ValueError:
                    pass       # malformed export: keep the old view

    # -------------------------------------------------------------- routing

    def _pick(self, tried: set,
              key: bytes | None = None) -> ReplicaState | None:
        with self._rlock:
            # pure prefill workers never take GENERATE traffic — a
            # decode on one would compile decode programs and break the
            # tier contract (decode and legacy 'both' replicas both can)
            cands = [r for r in self._replicas.values()
                     if r.breaker == "closed" and r.role != "prefill"
                     and r.replica_id not in tried]
            if not cands:
                # no closed replica left: a HALF-OPEN one may carry trial
                # traffic — its success re-closes the breaker, its failure
                # re-opens it (the request still has its resubmit budget)
                cands = [r for r in self._replicas.values()
                         if r.breaker == "half_open"
                         and r.role != "prefill"
                         and r.replica_id not in tried]
            if not cands:
                return None
            if key is not None:
                # KEYED placement is rendezvous-hashed, not policy-picked
                # (docs/ROBUSTNESS.md "Control-plane HA"): routers with
                # the same healthy view independently compute the same
                # replica for a key — a resubmit through a DIFFERENT
                # router lands on the engine whose dedup table already
                # holds the request, with no shared routing state (and
                # only a transient breaker-view divergence can re-run a
                # key elsewhere). Random 16-byte keys spread uniformly,
                # and HRW moves only the affected keys on membership
                # churn; the `tried` fallback order matches across
                # routers too.
                return max(cands, key=lambda r: self._hrw(key, r))
            cands.sort(key=lambda r: r.replica_id)
            return POLICIES[self._policy](self, cands)

    @staticmethod
    def _hrw(key: bytes, r: ReplicaState) -> tuple:
        h = hashlib.blake2b(key + r.replica_id.encode(),
                            digest_size=8).digest()
        return (int.from_bytes(h, "little"), r.replica_id)

    @staticmethod
    def _request_key(arrays) -> bytes | None:
        """The GENERATE options array's 16-byte idempotency key (the
        7-wide options shape's trailing four int32 words), if present."""
        if len(arrays) >= 3:
            opts = np.asarray(arrays[2]).reshape(-1)
            if opts.size >= 7 and np.any(opts[3:7]):
                return np.ascontiguousarray(opts[3:7], np.int32).tobytes()
        return None

    @staticmethod
    def _trace_ctx(arrays) -> tuple[str | None, str | None]:
        """The GENERATE options array's fleet trace context — the 13-wide
        options shape's trailing TRACE_WORDS int32 words — as a
        ``(trace_id, parent_span)`` hex pair; ``(None, None)`` when no
        context rode the request (all-zero words)."""
        if len(arrays) >= 3:
            opts = np.asarray(arrays[2]).reshape(-1)
            if opts.size >= 13:
                return words_to_trace([int(w) for w in opts[7:13]])
        return None, None

    def _evict(self, r: ReplicaState, reason: str):
        with self._rlock:
            self._open_breaker_locked(r, reason)
        flight.record("router.evict", replica=r.replica_id, reason=reason)

    def _replica_op(self, r: ReplicaState, op: int, arrays=(),
                    timeout=None, client_conn=None):
        """One request/response exchange with a replica on a fresh authed
        connection. Returns the response arrays (GENERATE) or single
        payload array (STATS/PROMETHEUS). A connection per exchange is
        deliberate: the failure classification (`_classify_wire_error`)
        needs request/response isolation — a resubmitted request must
        never read a half-delivered response from a previous exchange —
        and it keeps the router stateless about replica sockets; a
        persistent-pool optimization would buy one connect RTT per
        request at the cost of desync tracking.

        ``client_conn`` (GENERATE only): while the replica decodes, the
        ROUTER's own client socket is watched; client EOF drops the
        replica connection — whose serve-side disconnect watch then
        cancels the request into its engine — and raises
        `_ClientDisconnected`. The disconnect chain composes across
        tiers: client -> router -> replica -> engine.cancel
        (docs/ROBUSTNESS.md "Cancellation")."""
        eff_timeout = timeout if timeout is not None \
            else self._request_timeout
        host, port = r.endpoint.rsplit(":", 1)
        sock = retrying_connect(host, int(port), timeout=eff_timeout,
                                attempts=2,
                                deadline_s=self._connect_deadline)
        sent = False
        try:
            sock.sendall(struct.pack("<I", MAGIC) + self._replica_token)
            sock.sendall(struct.pack("<III", MAGIC, op, len(arrays)))
            if arrays:
                send_arrays(sock, arrays)
            sent = True
            if client_conn is not None:
                self._await_replica_or_client_gone(sock, client_conn,
                                                   eff_timeout)
            magic, status, n = struct.unpack(
                "<III", _recv_exact(sock, 12))
            if magic != MAGIC:
                raise ConnectionError(
                    f"bad magic from replica {r.replica_id} (auth "
                    f"mismatch drops the connection — check "
                    f"replica_secret)")
            if status != 0:
                msg = _recv_exact(sock, n).decode(errors="replace")
                raise _classify_wire_error(msg)
            outs = recv_arrays(sock, n)
            return outs if op == OP_GENERATE else outs[0]
        except (ConnectionError, socket.timeout, OSError) as e:
            # a wire death AFTER the request was delivered is AMBIGUOUS:
            # the replica may be running — or may already have finished —
            # the work. `_route_generate` gives a keyed request one
            # same-replica retry on this (the dedup table resolves the
            # ambiguity); everything else keeps the evict+resubmit path
            if sent and not isinstance(e, ReplicaUnavailable):
                # a classified ReplicaUnavailable is an ANSWER (the
                # replica refused the work) — definitive, not ambiguous
                e._pt_ambiguous = True
            raise
        finally:
            sock.close()

    @staticmethod
    def _await_replica_or_client_gone(sock, conn, timeout):
        """Block until the replica's response STARTS, peeking the
        router's own client socket each cycle (`serve.peek_disconnect` —
        the same liveness idiom serve's GENERATE wait uses, shared so the
        two tiers of the disconnect chain cannot drift). On client EOF:
        count it and raise — the enclosing finally closes the replica
        socket, which is exactly the disconnect the replica's serve-side
        watch turns into an engine cancel."""
        import select as _select

        from paddle_tpu.inference.serve import peek_disconnect
        t_end = time.monotonic() + timeout
        watch = True
        while True:
            readable, _, _ = _select.select([sock], [], [], 0.25)
            if readable:
                return
            if watch:
                state = peek_disconnect(conn)
                if state == "pipelined":
                    watch = False
                elif state == "gone":
                    metrics.counter("router.disconnect_drops").inc()
                    raise _ClientDisconnected(
                        "client disconnected mid-GENERATE (replica "
                        "connection dropped; the replica cancels)")
            if time.monotonic() >= t_end:
                raise socket.timeout(
                    "timed out waiting for replica response")

    @staticmethod
    def _deadline_ms(arrays) -> int | None:
        """The GENERATE options array's deadline_ms (> 0), if present."""
        if len(arrays) >= 3:
            opts = np.asarray(arrays[2]).reshape(-1)
            if opts.size >= 3 and int(opts[2]) > 0:
                return int(opts[2])
        return None

    def _route_generate(self, arrays, conn=None) -> list[np.ndarray]:
        """Forward one GENERATE to a policy-picked replica; on replica
        failure open its breaker and resubmit elsewhere, up to
        ``max_resubmits`` times. A request carrying a deadline forwards
        its REMAINING budget on every attempt (and clips the attempt's IO
        timeout to it), so resubmission can never stretch a request past
        its deadline. Raises to the client only when the budget, the
        deadline, or the healthy set is exhausted (or the request itself
        is bad) — always one clean typed line, never a hang.

        A request carrying an idempotency KEY routes by rendezvous hash
        (`_pick`), forwards the CLIENT's key on every attempt (never a
        per-attempt identity), and treats an ambiguous mid-wire death —
        the request was delivered, the answer never arrived — as ONE
        free same-replica retry: the replica's dedup table attaches or
        replays, so the ambiguity costs zero duplicate generations and
        no eviction (docs/ROBUSTNESS.md "Control-plane HA")."""
        rid_req = new_request_id()
        budget = self._max_resubmits
        tried: set[str] = set()
        key = self._request_key(arrays)
        trace_id, client_span = self._trace_ctx(arrays)
        router_span = None
        if trace_id is not None:
            # re-parent the forwarded context to THIS hop's span id so the
            # replica's spans chain client -> router -> replica; the trace
            # id itself is forwarded verbatim on every attempt (resubmits
            # and ack-retries reuse the rewritten options array)
            router_span = new_span_id()
            arrays = list(arrays)
            opts = np.array(np.asarray(arrays[2]).reshape(-1), np.int32,
                            copy=True)
            opts[7:13] = trace_to_words(trace_id, router_span)
            arrays[2] = opts
        retried_same: set[str] = set()
        forced: ReplicaState | None = None
        t0 = time.perf_counter()
        deadline_ms = self._deadline_ms(arrays)
        t_deadline = None if deadline_ms is None \
            else time.monotonic() + deadline_ms / 1000.0
        last_err = None
        overloads = others = 0
        if self._disagg_ready():
            # two-phase flow first (docs/SERVING.md "Disaggregated
            # serving"); a None return — the prefill tier failed or died
            # mid-stream — falls back to the symmetric loop below, which
            # prefills on the decode-capable replica itself. Terminal
            # outcomes raise straight through.
            outs = self._route_disagg(arrays, conn, key, t_deadline,
                                      deadline_ms, rid_req, t0,
                                      (trace_id, client_span, router_span))
            if outs is not None:
                return outs
            metrics.counter("router.disagg_fallbacks").inc()
            flight.record("router.disagg_fallback", request_id=rid_req)
        while True:
            fwd, timeout = arrays, None
            if t_deadline is not None:
                remaining = t_deadline - time.monotonic()
                if remaining <= 0:
                    metrics.counter("router.deadline_exceeded").inc()
                    raise DeadlineExceeded(
                        f"request deadline ({deadline_ms} ms) exhausted "
                        f"after {len(tried)} attempt(s)"
                        + (f"; last replica error: {last_err}"
                           if last_err else ""))
                # forward the REMAINING budget, not the original: the
                # replica's engine must expire the request by the
                # CLIENT's clock, resubmits included
                fwd = list(arrays)
                opts = np.array(np.asarray(arrays[2]).reshape(-1),
                                np.int32, copy=True)
                opts[2] = max(1, int(remaining * 1000))
                fwd[2] = opts
                # grace past the replica's own deadline handling: the
                # engine answers DeadlineExceeded first; the clip only
                # catches a wedged replica
                timeout = min(self._request_timeout, remaining + 10.0)
            r, forced = forced if forced is not None \
                else self._pick(tried, key=key), None
            if r is None:
                if overloads and not others:
                    # every reachable replica answered a typed shed:
                    # relay ONE typed Overloaded line (retryable-later),
                    # not a router-internal wrapper
                    metrics.counter("router.shed").inc()
                    raise Overloaded(
                        f"all replicas shedding load; last: {last_err}")
                metrics.counter("router.no_replica").inc()
                raise RuntimeError(
                    "router: no healthy replica available"
                    + (f" (last error from {last_err})" if last_err
                       else ""))
            with self._rlock:
                r.outstanding += 1
                r._g_out.set(r.outstanding)
            try:
                outs = self._replica_op(r, OP_GENERATE, fwd,
                                        timeout=timeout, client_conn=conn)
            except (ReplicaUnavailable, ConnectionError, socket.timeout,
                    OSError) as e:
                last_err = f"{r.replica_id}: {type(e).__name__}: {e}"
                metrics.counter("router.replica_errors").inc()
                if key is not None and getattr(e, "_pt_ambiguous", False) \
                        and r.replica_id not in retried_same:
                    # AMBIGUOUS wire death on a KEYED request: the replica
                    # got the request and may be decoding (or done) — a
                    # resubmit elsewhere would duplicate the generation.
                    # Retry the SAME replica once, free of eviction and
                    # resubmit budget: its dedup table attaches/replays.
                    # A replica that is actually dead fails the retry's
                    # CONNECT (unambiguous) and takes the normal
                    # evict+resubmit path below.
                    retried_same.add(r.replica_id)
                    forced = r
                    metrics.counter("router.ack_retries").inc()
                    flight.record("router.ack_retry",
                                  replica=r.replica_id, error=last_err)
                    continue
                if isinstance(e, ReplicaUnavailable) \
                        and str(e).startswith("Overloaded"):
                    overloads += 1     # healthy replica, full queue: no
                    #                    breaker action, try elsewhere
                else:
                    others += 1
                if _should_evict(e):
                    self._evict(r, f"{type(e).__name__}: {e}")
                tried.add(r.replica_id)
                if budget <= 0:
                    if overloads and not others:
                        metrics.counter("router.shed").inc()
                        raise Overloaded(
                            f"all replicas shedding load; last: "
                            f"{last_err}") from e
                    raise RuntimeError(
                        f"router: resubmit budget "
                        f"({self._max_resubmits}) exhausted; last "
                        f"replica error: {last_err}") from e
                budget -= 1
                metrics.counter("router.resubmits").inc()
                continue
            finally:
                with self._rlock:
                    r.outstanding -= 1
                    r._g_out.set(r.outstanding)
            with self._rlock:
                r.consec_fail = 0
                # half-open trial succeeded: the replica is back. ONLY
                # half-open — a success that was in flight when another
                # request's failure opened the breaker must not re-close
                # it with zero cooldown (same stale-success guard as
                # `_record_probe`)
                if r.breaker == "half_open":
                    r.breaker = "closed"
                    metrics.counter("router.breaker_close").inc()
                    flight.record("router.breaker",
                                  replica=r.replica_id, state="closed")
            dt = time.perf_counter() - t0
            metrics.counter("router.requests").inc()
            metrics.counter("router.replica_requests",
                            replica=r.replica_id).inc()
            metrics.histogram("router.request_seconds").observe(dt)
            metrics.add_span("router.forward", t0, dt, cat="router",
                             args={"request_id": rid_req,
                                   "replica": r.replica_id},
                             trace_id=trace_id, parent=client_span,
                             span_id=router_span)
            return outs

    # ------------------------------------------------ disaggregated routing

    def _disagg_ready(self) -> bool:
        """The two-phase flow needs BOTH tiers healthy: >= 1 closed
        prefill worker and >= 1 closed decode-capable replica. Anything
        less routes symmetric — disaggregation is an optimization, never
        an availability dependency."""
        with self._rlock:
            has_p = any(r.breaker == "closed" and r.role == "prefill"
                        for r in self._replicas.values())
            has_d = any(r.breaker == "closed"
                        and r.role in ("decode", "both")
                        for r in self._replicas.values())
        return has_p and has_d

    def _pick_prefill(self, hashes):
        """``(replica, affinity_hit)``: the prefill worker for this
        prompt. The fleet directory biases shared-prefix traffic to the
        worker whose store already holds the longest prefix (the prompt
        then prefills only its uncached tail — a system prompt costs the
        FLEET one prefill); a miss falls back to the placement policy.
        Fault site ``router.stale_directory`` forces a deliberately
        stale affinity route (deterministic staleness drill: the worker
        just prefills the whole prompt — correctness never depended on
        the directory)."""
        with self._rlock:
            cands = [r for r in self._replicas.values()
                     if r.breaker == "closed" and r.role == "prefill"]
            if not cands:
                return None, False
            cands.sort(key=lambda r: r.replica_id)
            if faults.ENABLED and faults.fire("router.stale_directory"):
                metrics.counter("router.stale_affinity").inc()
                return cands[-1], True
            if hashes:
                rid, depth = self._directory.lookup(hashes)
                if rid is not None:
                    for r in cands:
                        if r.replica_id == rid:
                            spilled = self._directory.is_spilled(
                                hashes[depth - 1], rid)
                            if spilled:
                                # the hit's deepest page lives in a spill
                                # tier: this route trades a re-upload for
                                # a fleet-wide re-prefill
                                metrics.counter(
                                    "router.affinity_spilled").inc()
                            flight.record("router.affinity",
                                          replica=rid, depth=depth,
                                          spilled=spilled)
                            return r, True
            return POLICIES[self._policy](self, cands), False

    def _pick_decode(self, key):
        """The decode replica for a disaggregated request: dedicated
        decode tier first, legacy 'both' replicas as the fallback pool.
        Keyed requests keep their rendezvous-hash placement so a
        failover resubmit lands on the engine whose dedup table owns the
        key (docs/ROBUSTNESS.md "Control-plane HA")."""
        with self._rlock:
            cands = [r for r in self._replicas.values()
                     if r.breaker == "closed" and r.role == "decode"]
            if not cands:
                cands = [r for r in self._replicas.values()
                         if r.breaker == "closed" and r.role == "both"]
            if not cands:
                return None
            if key is not None:
                return max(cands, key=lambda r: self._hrw(key, r))
            cands.sort(key=lambda r: r.replica_id)
            return POLICIES[self._policy](self, cands)

    def _open_replica(self, r: ReplicaState, timeout):
        """Fresh authed replica connection (the disagg exchanges manage
        their own sockets — one prefill stream feeds one decode stream,
        so the request/response isolation of `_replica_op` does not
        fit)."""
        host, port = r.endpoint.rsplit(":", 1)
        sock = retrying_connect(host, int(port), timeout=timeout,
                                attempts=2,
                                deadline_s=self._connect_deadline)
        sock.sendall(struct.pack("<I", MAGIC) + self._replica_token)
        return sock

    def _route_disagg(self, arrays, conn, key, t_deadline, deadline_ms,
                      rid_req, t0, trace3=(None, None, None)):
        """One two-phase GENERATE (docs/SERVING.md "Disaggregated
        serving"): OP_PREFILL to the affinity-picked prefill worker,
        whose PTKS1 page records RELAY to the chosen decode replica's
        OP_KV_STREAM as they are produced — the decode replica admits
        the slot the moment the final record lands and answers the full
        sequence, token-identical to a symmetric route. Deadlines
        forward as remaining budget, the cancel tag and idempotency key
        ride the stream options, and the client-disconnect watch covers
        the prefill wait, the record relay AND the decode wait (a
        client hanging up mid-prefill drops both sockets — the fleet
        stops paying immediately). One honest window: a CANCEL by tag
        that arrives while the prefill is still streaming is a clean
        miss — the tag registers on the decode replica with the stream
        options — so the request runs to completion; the disconnect
        chain is what bounds an abandoned client's cost.

        Returns the response arrays, or None to FALL BACK to symmetric
        routing (prefill worker dead/mid-stream death/no tier capacity)
        — the decode side discards a partial stream with its pool
        untouched, and the caller re-runs the prompt as a plain
        GENERATE. Terminal per-request outcomes (validation errors,
        DeadlineExceeded, Cancelled, client disconnect) raise through
        verbatim; they would be identical on any route."""
        trace_id, client_span, router_span = trace3
        # both tiers' spans parent on the router hop: the prefill worker's
        # engine.prefill_stream AND the decode replica's request spans
        # chain under one router.forward — the stitched trace shows the
        # two-phase fan-out as siblings, not a linear chain
        twords = trace_to_words(trace_id, router_span) \
            if trace_id is not None else None
        prompt = np.ascontiguousarray(np.asarray(arrays[0]).reshape(-1),
                                      np.int32)
        mnt = int(np.asarray(arrays[1]).reshape(-1)[0])
        cache, spec = 1, 1
        if len(arrays) >= 3:
            opts = np.asarray(arrays[2]).reshape(-1)
            cache, spec = int(opts[0]), int(opts[1])
        tag = np.ascontiguousarray(arrays[3], np.uint8).reshape(-1) \
            if len(arrays) == 4 else np.zeros(0, np.uint8)
        hashes = prompt_page_hashes(prompt, self._page_size) \
            if (self._page_size and cache) else []
        pre, hit = self._pick_prefill(hashes)
        dec = self._pick_decode(key)
        if pre is None or dec is None:
            return None
        metrics.counter("router.disagg_requests").inc()
        (metrics.counter("router.affinity_hits") if hit
         else metrics.counter("router.affinity_misses")).inc()
        timeout = self._request_timeout
        remaining_ms = 0
        if t_deadline is not None:
            remaining = t_deadline - time.monotonic()
            if remaining <= 0:
                metrics.counter("router.deadline_exceeded").inc()
                raise DeadlineExceeded(
                    f"request deadline ({deadline_ms} ms) exhausted "
                    f"before the prefill tier was reached")
            remaining_ms = max(1, int(remaining * 1000))
            timeout = min(self._request_timeout, remaining + 10.0)
        opts_kv = [mnt, cache, spec, remaining_ms]
        if key is not None or twords is not None:
            # the trace words ride PAST the key slot, so a traced keyless
            # request pads four zero key words (serve's parser ignores an
            # all-zero key group)
            opts_kv += ([int(w) for w in np.frombuffer(key, np.int32)]
                        if key is not None else [0, 0, 0, 0])
        if twords is not None:
            opts_kv += twords
        # 1. start the prefill stream
        psock = None
        try:
            psock = self._open_replica(pre, timeout)
            psock.settimeout(timeout)
            psock.sendall(struct.pack("<III", MAGIC, OP_PREFILL, 2))
            popts = [cache] + twords if twords is not None else [cache]
            send_arrays(psock, [prompt, np.asarray(popts, np.int32)])
            if conn is not None:
                # watch the CLIENT while the worker plans the stream —
                # same disconnect chain as the decode wait
                self._await_replica_or_client_gone(psock, conn, timeout)
            magic, status, n_records = struct.unpack(
                "<III", _recv_exact(psock, 12))
            if magic != MAGIC:
                raise ConnectionError(
                    f"bad magic from prefill worker {pre.replica_id}")
            if status != 0:
                msg = _recv_exact(psock, n_records).decode(
                    errors="replace")
                raise _classify_wire_error(msg)
        except (_ReplicaAppError, _ClientDisconnected):
            if psock is not None:
                psock.close()
            raise                    # identical on any route / nobody left
        except (ReplicaUnavailable, ConnectionError, socket.timeout,
                OSError) as e:
            if psock is not None:
                psock.close()
            metrics.counter("router.replica_errors").inc()
            if _should_evict(e):
                self._evict(pre, f"prefill: {type(e).__name__}: {e}")
            return None
        # 2. relay records to the decode replica as they are produced,
        #    then await its answer (client-disconnect watched)
        dsock = None
        with self._rlock:
            dec.outstanding += 1
            dec._g_out.set(dec.outstanding)
        try:
            try:
                dsock = self._open_replica(dec, timeout)
                dsock.settimeout(timeout)
                dsock.sendall(struct.pack("<III", MAGIC, OP_KV_STREAM,
                                          2 + int(n_records)))
                send_arrays(dsock, [np.asarray(opts_kv, np.int32), tag])
            except (ConnectionError, socket.timeout, OSError) as e:
                metrics.counter("router.replica_errors").inc()
                if _should_evict(e):
                    self._evict(dec, f"decode: {type(e).__name__}: {e}")
                return None
            try:
                for _ in range(int(n_records)):
                    try:
                        # the client-disconnect watch covers the RELAY
                        # too: a client hanging up 100 ms into a 30 s
                        # prefill must stop the fleet paying for it —
                        # dropping both sockets cancels the decode side
                        # (its disconnect watch) and orphans the prefill
                        # stream. _ClientDisconnected is not a wire
                        # error and propagates past the except below.
                        if conn is not None:
                            self._await_replica_or_client_gone(
                                psock, conn, timeout)
                        (rec,) = recv_arrays(psock, 1)
                    except (ConnectionError, socket.timeout, OSError,
                            struct.error) as e:
                        # MID-STREAM prefill-worker death: drop both
                        # sockets — the decode replica discards the
                        # partial stream with its pool at baseline —
                        # and fall back to symmetric prefill
                        metrics.counter("router.replica_errors").inc()
                        metrics.counter("router.stream_aborts").inc()
                        flight.record("router.stream_abort",
                                      request_id=rid_req,
                                      prefill=pre.replica_id,
                                      error=f"{type(e).__name__}: {e}")
                        self._evict(pre, f"prefill stream died: "
                                         f"{type(e).__name__}: {e}")
                        return None
                    try:
                        send_arrays(dsock, [rec])
                    except (ConnectionError, socket.timeout, OSError) \
                            as e:
                        # the DECODE wire died under the relay: that is
                        # the decode replica's failure, not the prefill
                        # worker's — evict the right breaker
                        metrics.counter("router.replica_errors").inc()
                        metrics.counter("router.stream_aborts").inc()
                        flight.record("router.stream_abort",
                                      request_id=rid_req,
                                      decode=dec.replica_id,
                                      error=f"{type(e).__name__}: {e}")
                        self._evict(dec, f"decode stream died: "
                                         f"{type(e).__name__}: {e}")
                        return None
            finally:
                psock.close()
                psock = None
            try:
                if conn is not None:
                    self._await_replica_or_client_gone(dsock, conn,
                                                       timeout)
                magic, status, n = struct.unpack(
                    "<III", _recv_exact(dsock, 12))
                if magic != MAGIC:
                    raise ConnectionError(
                        f"bad magic from decode replica "
                        f"{dec.replica_id}")
                if status != 0:
                    msg = _recv_exact(dsock, n).decode(errors="replace")
                    raise _classify_wire_error(msg)
                outs = recv_arrays(dsock, n)
            except _ReplicaAppError:
                raise      # DeadlineExceeded/Cancelled/validation: relay
            except (ReplicaUnavailable, ConnectionError, socket.timeout,
                    OSError) as e:
                metrics.counter("router.replica_errors").inc()
                if _should_evict(e):
                    self._evict(dec, f"decode: {type(e).__name__}: {e}")
                return None
        finally:
            if psock is not None:
                psock.close()
            if dsock is not None:
                dsock.close()
            with self._rlock:
                dec.outstanding -= 1
                dec._g_out.set(dec.outstanding)
        # success bookkeeping: the worker's store now holds this
        # prompt's pages — register them so the NEXT shared-prefix
        # request routes with affinity even before the STATS pull
        if hashes:
            self._directory.register(hashes, pre.replica_id)
        with self._rlock:
            for r in (pre, dec):
                r.consec_fail = 0
                if r.breaker == "half_open":
                    r.breaker = "closed"
                    metrics.counter("router.breaker_close").inc()
        dt = time.perf_counter() - t0
        metrics.counter("router.requests").inc()
        metrics.counter("router.replica_requests",
                        replica=dec.replica_id).inc()
        metrics.histogram("router.request_seconds").observe(dt)
        metrics.add_span("router.forward", t0, dt, cat="router",
                         args={"request_id": rid_req,
                               "replica": dec.replica_id,
                               "prefill": pre.replica_id},
                         trace_id=trace_id, parent=client_span,
                         span_id=router_span)
        return outs

    def _route_cancel(self, arrays) -> np.ndarray:
        """CANCEL op: the router is stateless about which replica holds a
        tag, so the cancel fans out to every non-open replica; the one
        holding live work answers 1 (docs/ROBUSTNESS.md). Probe-grade
        timeouts — a cancel must never cost a request timeout."""
        if len(arrays) != 1:
            raise ValueError(
                f"CANCEL wants one uint8 tag array, got {len(arrays)}")
        with self._rlock:
            # EVERY replica, open breakers included: a breaker opened by
            # an unrelated transient failure can still hold the live
            # request this cancel is for, and a cancel is cheap and
            # idempotent — a dead endpoint just times out at probe grade
            reps = list(self._replicas.values())
        hits: list[int] = []

        def _one(rep):
            try:
                out = self._replica_op(
                    rep, OP_CANCEL, arrays,
                    timeout=min(self._connect_deadline, 2.0) + 3.0)
                hits.append(int(np.asarray(out).reshape(-1)[0]))
            except (OSError, ConnectionError, RuntimeError):
                pass        # a cancel miss must never become an error
        # concurrent fan-out: cancellation latency is the slowest single
        # replica, not the sum — one wedged replica must not delay the
        # cancel reaching the replica actually holding the work
        ths = [threading.Thread(target=_one, args=(rep,), daemon=True)
               for rep in reps]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        metrics.counter("router.cancels").inc()
        return np.asarray([1 if any(hits) else 0], np.int32)

    # ------------------------------------------------------------ wire side

    def attach_fleet(self, fleet):
        """Feed ``fleet`` (an `observability.fleet.FleetMetrics`) from
        this router's STATS poll loop: every per-replica snapshot the
        loop pulls is ingested with its ``{role, replica}`` identity, so
        the fleet rollup rides the existing scrape instead of adding a
        second one. Returns ``self`` for chaining."""
        self._fleet = fleet
        return self

    def attach_slo(self, evaluator):
        """Evaluate ``evaluator`` (an `observability.slo.SLOEvaluator`,
        scope ``"fleet"``) against the fleet rollup after every stats
        poll. Needs `attach_fleet` — the rollup is the snapshot the
        evaluator windows over. Returns ``self`` for chaining."""
        self._slo = evaluator
        return self

    def attach_registry(self, lease):
        """Hold the ROUTER-ROLE registry lease this router registered
        under (node id ``router:<id>``, `elastic.router_node_id`):
        clients discover the redundant router set from these leases
        (`RemotePredictor(registry_dir=...)`), sibling routers and the
        replicas' peer discovery skip them by role. `stop()` deregisters
        so a cleanly stopped router leaves the failover set."""
        self._lease = lease
        return self

    def serve_forever(self):
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.5)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            with self._conn_lock:
                self._conns.add(conn)
            threading.Thread(target=self._client_loop, args=(conn,),
                             daemon=True).start()
        self._sock.close()

    def stop(self, hard=False):
        """Stop accepting. ``hard=True`` additionally severs every LIVE
        client connection — the router-kill drill's process-death
        equivalent: blocked clients see EOF and fail over to a surviving
        router (docs/ROBUSTNESS.md "Control-plane HA")."""
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._lease is not None:
            try:
                self._lease.leave()
            except OSError:
                pass
            self._lease = None
        if hard:
            with self._conn_lock:
                conns = list(self._conns)
            for c in conns:
                try:
                    c.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    c.close()
                except OSError:
                    pass

    def _client_loop(self, conn):
        """Same protocol discipline as `InferenceServer._client_loop`:
        authed hello, then ops; any error mid-request reports and drops
        the connection (stream position is unknowable after a partial
        body). The framing/auth skeleton is intentionally a sibling copy
        of serve's loop for now — the op BODIES differ everywhere (local
        predictor/engine vs forwarding) and serve's loop is interwoven
        with them; extracting a shared protocol-server core is the
        follow-up that should ride the next wire-protocol change."""
        import hmac
        try:
            try:
                conn.settimeout(10.0)
                hello = _recv_exact(conn, 4 + 32)
            except (ConnectionError, socket.timeout):
                return
            (magic,) = struct.unpack("<I", hello[:4])
            if magic != MAGIC or not hmac.compare_digest(hello[4:],
                                                         self._token):
                return
            conn.settimeout(None)
            while not self._stop.is_set():
                try:
                    head = _recv_exact(conn, 12)
                except ConnectionError:
                    return
                magic, op, n = struct.unpack("<III", head)
                if magic != MAGIC:
                    self._send_err(conn, "bad magic")
                    return
                if op == OP_PING:
                    conn.sendall(struct.pack("<III", MAGIC, 0, 0))
                    continue
                if op == OP_STATS:
                    # the ROUTER's registry: router.* counters, per-replica
                    # outstanding gauges, plus anything else this process
                    # recorded
                    conn.sendall(struct.pack("<III", MAGIC, 0, 1))
                    send_arrays(conn, [stats_payload(
                        {"role": "router",
                         "node": metrics.node_identity()})])
                    continue
                if op == OP_PROMETHEUS:
                    conn.sendall(struct.pack("<III", MAGIC, 0, 1))
                    send_arrays(conn, [np.frombuffer(
                        metrics.to_prometheus().encode(),
                        dtype=np.uint8).copy()])
                    continue
                if op == OP_TRACE_EXPORT:
                    # the router is a trace participant too: its
                    # router.forward spans stitch into the same fleet
                    # timeline the replicas export
                    arrays = recv_arrays(conn, n)
                    if len(arrays) != 1:
                        self._send_err(conn, "ValueError: TRACE_EXPORT "
                                             "wants one uint8 trace-id "
                                             "array")
                        return
                    tid = np.ascontiguousarray(
                        arrays[0], np.uint8).tobytes().hex()
                    conn.sendall(struct.pack("<III", MAGIC, 0, 1))
                    send_arrays(conn, [trace_export_payload(tid)])
                    continue
                if op == OP_DEBUG_DUMP:
                    recv_arrays(conn, n)
                    conn.sendall(struct.pack("<III", MAGIC, 0, 1))
                    send_arrays(conn, [debug_dump_payload()])
                    continue
                if op == OP_SHUTDOWN:
                    conn.sendall(struct.pack("<III", MAGIC, 0, 0))
                    self.stop()
                    return
                if faults.ENABLED and op == OP_GENERATE \
                        and faults.fire("router.crash"):
                    # deterministic router death at request accept
                    # (testing/faults.py): the listener closes, every
                    # live client connection severs, and this request is
                    # never forwarded — clients must fail over to a
                    # surviving router (docs/ROBUSTNESS.md)
                    self.stop(hard=True)
                    return
                try:
                    arrays = recv_arrays(conn, n)
                    if op == OP_RUN:
                        raise RuntimeError(
                            "router fronts GENERATE/CANCEL/STATS/"
                            "PROMETHEUS only; RUN needs a direct replica "
                            "connection")
                    if op == OP_CANCEL:
                        outs = [self._route_cancel(arrays)]
                    elif op == OP_GENERATE:
                        outs = self._route_generate(arrays, conn=conn)
                    else:
                        raise RuntimeError(f"unknown op {op}")
                    conn.sendall(
                        struct.pack("<III", MAGIC, 0, len(outs)))
                    send_arrays(conn, outs)
                except Exception as e:  # noqa: BLE001 — wire to client
                    metrics.counter("router.errors").inc()
                    # relay replica app errors VERBATIM: the client (or a
                    # second-tier router classifying by prefix) must see
                    # exactly what a direct replica connection would send.
                    # Router-raised typed errors (Overloaded,
                    # DeadlineExceeded) format as the same one-line
                    # "<Type>: <text>" a replica would send
                    msg = str(e) if isinstance(e, _ReplicaAppError) \
                        else f"{type(e).__name__}: {e}"
                    try:
                        self._send_err(conn, msg)
                    except OSError:
                        pass    # client already gone
                    return
        finally:
            with self._conn_lock:
                self._conns.discard(conn)
            conn.close()

    @staticmethod
    def _send_err(conn, msg):
        raw = msg.encode()
        conn.sendall(struct.pack("<III", MAGIC, 1, len(raw)) + raw)


def main(argv=None):
    ap = argparse.ArgumentParser("paddle_tpu.serving.router")
    ap.add_argument("--registry-dir", default=None,
                    help="shared-filesystem elastic registry to watch for "
                         "replica membership (observer mode)")
    ap.add_argument("--registry-addr", default=None,
                    help="host:port of a TcpRegistryServer to watch "
                         "(needs PADDLE_ELASTIC_TOKEN)")
    ap.add_argument("--replica", action="append", default=[],
                    metavar="ID=HOST:PORT",
                    help="static replica entry (repeatable; composes with "
                         "the registry)")
    ap.add_argument("--policy", default="round_robin",
                    choices=sorted(POLICIES))
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--auth-name", default=None,
                    help="router's client-facing auth secret; default "
                         "PADDLE_SERVE_TOKEN or a random token printed "
                         "once as 'TOKEN <hex>'")
    ap.add_argument("--replica-secret", default=None,
                    help="fleet-shared replica auth secret (each "
                         "replica's --auth-name); default "
                         "PADDLE_SERVE_TOKEN")
    ap.add_argument("--poll-interval", type=float, default=1.0)
    ap.add_argument("--max-resubmits", type=int, default=2)
    ap.add_argument("--page-size", type=int, default=None,
                    help="fleet KV page size, keys the prefix-affinity "
                         "directory's prompt hashing (default: learned "
                         "from the first engine STATS pull)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="also serve GET /metrics (Prometheus text) from "
                         "a stdlib HTTP endpoint on this port")
    ap.add_argument("--fleet-port", type=int, default=None,
                    help="serve the FLEET metrics plane on this port: "
                         "GET /metrics is every replica's registry "
                         "re-labeled {role,replica} plus fleet rollups, "
                         "GET /fleet is the JSON snapshot the autoscaler "
                         "shares (docs/OBSERVABILITY.md)")
    ap.add_argument("--slo", action="append", default=[],
                    metavar="NAME=OBJECTIVE[;OPTS]",
                    help="declare a fleet-scope SLO evaluated over the "
                         "fleet rollup after every stats poll (needs "
                         "--fleet-port); e.g. "
                         "'ttft=serve.ttft_seconds p99 < 2.0s;fast=60;"
                         "slow=300'. Repeatable. Alerts ride GET /alerts "
                         "on the fleet port (docs/OBSERVABILITY.md)")
    ap.add_argument("--dump", default=None, metavar="REPLICA_ID",
                    help="one-shot: pull REPLICA_ID's DEBUG_DUMP (flight "
                         "ring + metrics snapshot) through the replica "
                         "auth path, print the JSON, and exit")
    ap.add_argument("--router-id", default=None,
                    help="register THIS router in the registry under the "
                         "'router' role (node id router:<id>) so clients "
                         "discover the redundant router set "
                         "(RemotePredictor registry_dir=/registry_addr=); "
                         "default: watch-only, no self-registration")
    ap.add_argument("--advertise", default=None,
                    help="endpoint to publish with --router-id (default "
                         "<host>:<bound port>)")
    args = ap.parse_args(argv)
    replicas = {}
    for spec in args.replica:
        rid, _, ep = spec.partition("=")
        if not ep:
            ap.error(f"--replica wants ID=HOST:PORT, got {spec!r}")
        replicas[rid] = ep
    registry = None
    if args.registry_dir:
        from paddle_tpu.distributed.fleet.elastic import NodeRegistry
        registry = NodeRegistry(args.registry_dir)
    elif args.registry_addr:
        from paddle_tpu.distributed.fleet.elastic import TcpNodeRegistry
        registry = TcpNodeRegistry(args.registry_addr)
    if registry is None and not replicas:
        ap.error("need --registry-dir, --registry-addr, or --replica")
    if args.router_id is not None and registry is None:
        ap.error("--router-id needs --registry-dir or --registry-addr "
                 "(the router role is a registry lease)")
    metrics.set_node_identity(
        role="router",
        node_id=router_node_id(args.router_id) if args.router_id
        else f"router-{os.getpid()}")
    router = Router(registry=registry, replicas=replicas,
                    policy=args.policy, host=args.host, port=args.port,
                    auth_name=args.auth_name,
                    replica_secret=args.replica_secret,
                    poll_interval_s=args.poll_interval,
                    max_resubmits=args.max_resubmits,
                    page_size=args.page_size)
    if args.dump is not None:
        # one-shot debug pull: membership was folded in synchronously by
        # the constructor, so a static or already-registered replica is
        # resolvable immediately
        import json as _json
        with router._rlock:
            rep = router._replicas.get(args.dump)
        if rep is None:
            router.stop()
            raise SystemExit(
                f"--dump: unknown replica {args.dump!r}; have "
                f"{router.replica_ids()}")
        payload = router._replica_op(rep, OP_DEBUG_DUMP)
        print(_json.dumps(_json.loads(payload.tobytes().decode()),
                          indent=2, sort_keys=True))
        router.stop()
        return
    if args.router_id is not None:
        from paddle_tpu.distributed.fleet.elastic import (NodeRegistry,
                                                          TcpNodeRegistry)
        nid = router_node_id(args.router_id)
        endpoint = args.advertise or f"{args.host}:{router.port}"
        if args.registry_dir:
            lease = NodeRegistry(args.registry_dir, nid, endpoint)
        else:
            lease = TcpNodeRegistry(args.registry_addr, nid, endpoint)
        lease.register()
        router.attach_registry(lease)
        print(f"REGISTERED {nid} {endpoint}", flush=True)
    from paddle_tpu.inference.serve import install_sigusr1_dump
    install_sigusr1_dump()
    print(f"LISTENING {router.port}", flush=True)
    if router.generated_secret is not None:
        print(f"TOKEN {router.generated_secret}", flush=True)
    if args.metrics_port is not None:
        from paddle_tpu.observability.prometheus import start_http_exporter
        exporter = start_http_exporter(host=args.host,
                                       port=args.metrics_port)
        print(f"METRICS {exporter.server_address[1]}", flush=True)
    if args.slo and args.fleet_port is None:
        ap.error("--slo needs --fleet-port (fleet-scope SLOs window the "
                 "fleet rollup and serve alerts from the fleet port)")
    if args.fleet_port is not None:
        from paddle_tpu.observability.fleet import (FleetMetrics,
                                                    start_fleet_exporter)
        fm = FleetMetrics()
        router.attach_fleet(fm)
        slo = None
        if args.slo:
            from paddle_tpu.observability.slo import (SLOEvaluator,
                                                      parse_slo)
            slo = SLOEvaluator([parse_slo(s) for s in args.slo],
                               scope="fleet")
            router.attach_slo(slo)
        fexp = start_fleet_exporter(fm, host=args.host,
                                    port=args.fleet_port, slo=slo)
        print(f"FLEET {fexp.server_address[1]}", flush=True)
    router.serve_forever()


if __name__ == "__main__":
    main()
