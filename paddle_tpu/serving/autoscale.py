"""Elastic autoscaling controller: close the loop the router left open.

The router sees load (queue depth, SLO histograms, shed/degradation
counters) and the registry sees membership, but through PR 10 the fleet
SIZE was an operator constant: overload could only shed, and idle replicas
burned chips. This controller (ROADMAP item 2) watches per-replica STATS
plus the router's own outstanding view and acts between ``min_replicas``
and ``max_replicas``:

- **Scale UP** when sustained pressure shows up — router outstanding per
  replica past ``up_outstanding_per_replica``, an engine queue past
  ``up_queue_depth``, a shed counter moving, or the degradation ladder at
  level >= 2 — by asking the pluggable LAUNCHER to spawn a replica and
  folding it into the router's rotation (`Router.add_static_replica`).
- **Scale DOWN** when the fleet is sustained-idle, by removing a
  launcher-owned replica from rotation FIRST and then draining it WITH
  LIVE MIGRATION (`InferenceServer.drain(migrate_peers=...)`): its
  in-flight requests export mid-decode as KV handoffs, resume
  token-identically on the surviving replicas, and the blocked clients see
  normal answers — scale-down costs zero client-visible errors
  (docs/SERVING.md "Live migration").

Flapping control is explicit: a decision needs ``hysteresis_ticks``
CONSECUTIVE agreeing observations, and each direction has its own
cooldown (``up_cooldown_s`` / ``down_cooldown_s``) measured from the last
action in EITHER direction — a spike can never bounce the fleet
up-down-up inside one cooldown window.

The launcher is deliberately pluggable (`CallbackLauncher`): tests and
the bench rung spawn in-process `InferenceServer` replicas; a deployment
launcher starts pods/VMs that self-register in the elastic registry. The
controller never touches device state — it only talks wire ops and
router membership, the same MPMD control-plane discipline as the router
itself (arxiv 2412.14374).

Observability (docs/OBSERVABILITY.md): ``autoscaler.ticks``,
``autoscaler.scale_ups``, ``autoscaler.scale_downs``,
``autoscaler.errors`` counters; ``autoscaler.replicas`` and
``autoscaler.pressure`` (outstanding per healthy replica) gauges; one
flight-recorder event per decision.
"""
from __future__ import annotations

import socket
import struct
import threading
import time
from dataclasses import dataclass

from paddle_tpu.observability import metrics
from paddle_tpu.observability.flight_recorder import flight

__all__ = ["AutoscalePolicy", "Autoscaler", "CallbackLauncher"]


@dataclass
class AutoscalePolicy:
    """Thresholds + flap control for one `Autoscaler` (docs/SERVING.md
    "Autoscaling").

    min_replicas / max_replicas : the fleet-size clamp the controller acts
                   inside; scale-down never drops below min even when idle
    up_outstanding_per_replica : router-tracked in-flight requests per
                   healthy replica at/over which the fleet is under-sized
    up_queue_depth : any replica's engine queue depth at/over which the
                   fleet is under-sized (queues mean decode can't keep up)
    down_outstanding_per_replica : per-replica outstanding at/under which
                   the fleet counts as idle (with zero queue, zero shed
                   movement and a quiet degradation ladder)
    hysteresis_ticks : CONSECUTIVE agreeing observations a decision needs
                   — one noisy poll can never resize the fleet
    up_cooldown_s / down_cooldown_s : minimum wall-clock since the last
                   scaling action (either direction) before acting again;
                   down is deliberately slower than up — adding capacity
                   late sheds traffic, removing it late only costs chips
    reap_open_ticks : consecutive ticks a LAUNCHER-OWNED replica's
                   breaker must stay open before the controller reaps it
                   (removes it from rotation and has the launcher kill
                   it) — a spawned replica that crashed on its own would
                   otherwise wedge the fleet: never drained (scale-down
                   picks healthy victims) yet counted against
                   ``max_replicas`` forever. Generous by default so a
                   transient probe blip never kills live capacity
    """
    min_replicas: int = 1
    max_replicas: int = 4
    up_outstanding_per_replica: float = 4.0
    up_queue_depth: float = 4.0
    down_outstanding_per_replica: float = 0.5
    hysteresis_ticks: int = 2
    up_cooldown_s: float = 5.0
    down_cooldown_s: float = 15.0
    reap_open_ticks: int = 10


class CallbackLauncher:
    """Pluggable replica lifecycle for the autoscaler.

    ``spawn_fn()`` -> ``(replica_id, "host:port")`` starts a replica and
    returns its rotation entry; ``drain_fn(replica_id, endpoint,
    peer_endpoints)`` -> bool drains it WITH live migration (the in-process
    flavor calls ``server.drain(migrate_peers=peer_endpoints)``; a
    deployment flavor SIGTERMs a pod started with ``--migrate-on-drain``)
    and reports whether the drain was clean."""

    def __init__(self, spawn_fn, drain_fn):
        self._spawn_fn = spawn_fn
        self._drain_fn = drain_fn

    def spawn(self):
        return self._spawn_fn()

    def drain(self, replica_id, endpoint, peer_endpoints):
        return self._drain_fn(replica_id, endpoint, peer_endpoints)


class Autoscaler:
    """Fleet-size controller over the router tier + one launcher.

    >>> scaler = Autoscaler(router, launcher, AutoscalePolicy(
    ...     max_replicas=3, hysteresis_ticks=1))
    >>> scaler.start()        # or call scaler.tick() from your own loop
    ...
    >>> scaler.stop()

    ``router`` is one `Router` or a LIST of redundant routers
    (docs/ROBUSTNESS.md "Control-plane HA"): observations read from the
    first (all routers converge on the same registry-driven view), while
    membership mutations — spawn joins, scale-down removals, crash reaps
    — fan out to EVERY router, so a launcher-owned static replica exists
    in each rotation and a drain victim stops receiving traffic from the
    whole control plane, not just one front door. Registry-registered
    replicas need no fan-out (every router polls the registry itself).

    ``stats_fn(endpoint) -> dict | None`` overrides the per-replica STATS
    pull (the default opens one authed STATS exchange per healthy replica
    per tick using ``replica_secret``); tests inject deterministic
    snapshots. ``fleet`` is the higher-level form of the same injection:
    pass the `observability.fleet.FleetMetrics` the router's poll loop
    already feeds (`Router.attach_fleet`) and the controller reads its
    `snapshot_for` view instead of opening its own per-replica STATS
    connections — one scrape loop serves routing, the /metrics rollup AND
    scaling, with identical decisions (the snapshot schema is exactly a
    direct STATS pull's; a member the plane has not scraped yet reads as
    a failed pull, which the tick already tolerates). `tick()` is
    synchronous and returns the action taken (``"up"``/``"down"``/None)
    so chaos tests drive decisions without a timing-dependent thread."""

    def __init__(self, router, launcher, policy: AutoscalePolicy | None
                 = None, interval_s: float = 1.0, replica_secret=None,
                 stats_fn=None, fleet=None):
        self._routers = list(router) if isinstance(router, (list, tuple)) \
            else [router]
        if not self._routers:
            raise ValueError("need >= 1 router")
        self._router = self._routers[0]    # the observation view
        self._launcher = launcher
        self.policy = policy or AutoscalePolicy()
        self._interval = float(interval_s)
        if stats_fn is not None and fleet is not None:
            raise ValueError("pass stats_fn OR fleet, not both")
        if fleet is not None:
            stats_fn = fleet.snapshot_for
        self._stats_fn = stats_fn if stats_fn is not None \
            else self._pull_stats
        from paddle_tpu.inference.serve import auth_token
        self._replica_token = auth_token(
            None if replica_secret is None else str(replica_secret))
        self._owned: dict[str, str] = {}   # spawned replica id -> endpoint
        # owned replicas removed from rotation whose drain FAILED: retried
        # every tick until the launcher succeeds — a replica the operator
        # pays for must never fall out of tracking (rid -> endpoint)
        self._pending_drain: dict[str, str] = {}
        # consecutive ticks each owned replica's breaker has been OPEN
        # (crash detection — see AutoscalePolicy.reap_open_ticks)
        self._open_streak: dict[str, int] = {}
        self._spawn_seq = 0
        self._up_votes = 0
        self._down_votes = 0
        self._last_action_t = float("-inf")
        # per-replica last-seen shed counters: a single fleet total would
        # corrupt the baseline whenever one replica's STATS pull failed
        # transiently (its counter vanishes from the sum, then reappears
        # as a phantom delta) — deltas are computed replica-by-replica
        # and a replica's first observation contributes zero
        self._last_shed: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._m_ticks = metrics.counter("autoscaler.ticks")
        self._m_ups = metrics.counter("autoscaler.scale_ups")
        self._m_downs = metrics.counter("autoscaler.scale_downs")
        self._m_errors = metrics.counter("autoscaler.errors")
        self._g_replicas = metrics.gauge("autoscaler.replicas")
        self._g_pressure = metrics.gauge("autoscaler.pressure")
        self._g_pending = metrics.gauge("autoscaler.pending_drains")

    # ----------------------------------------------------------- observing

    def _pull_stats(self, endpoint: str) -> dict | None:
        """One authed STATS exchange at probe-grade timeouts (single
        connect attempt, short deadline) through the wire client —
        the STATS framing lives in ONE module; None on any failure — a
        dead replica's stats must age out of the decision, not stall
        the control loop."""
        from paddle_tpu.inference.serve import RemotePredictor
        try:
            host, port = endpoint.rsplit(":", 1)
            cli = RemotePredictor(host, int(port), timeout=4.0,
                                  token=self._replica_token,
                                  connect_retries=1, retry_deadline_s=2.0)
        except (OSError, ConnectionError, ValueError):
            return None
        try:
            return cli.stats()
        except (OSError, ConnectionError, ValueError, struct.error,
                socket.timeout):
            return None
        finally:
            cli.close()

    def observe(self) -> dict:
        """One fleet observation: the router's outstanding view plus each
        healthy replica's engine-side pressure gauges. ``n`` counts the
        HEALTHY (breaker-closed) replicas — the capacity actually serving
        — while ``n_total`` counts every rotation entry PLUS any
        pending-drain replicas: the size clamps bound what the operator
        PAYS for, so neither a transiently-open breaker nor a
        not-yet-confirmed drain may let the controller spawn past
        ``max_replicas``."""
        full = self._router.replica_view()
        view = [r for r in full if r["breaker"] == "closed"]
        outstanding = sum(r["outstanding"] for r in view)
        queue_depth = 0.0
        degradation = 0.0
        shed_delta = 0.0
        in_view = set()
        # the pulls are independent blocking wire exchanges: fan them out
        # so one dead-but-breaker-closed replica (probe hasn't hit its
        # threshold yet) stalls the tick by ONE probe budget, not one per
        # corpse — a scale-up decision delayed is exactly the overload
        # the controller exists to prevent
        snaps: dict[str, dict | None] = {}
        if len(view) > 1:
            def _pull(rid, ep):
                snaps[rid] = self._stats_fn(ep)
            ths = [threading.Thread(target=_pull, daemon=True,
                                    args=(r["replica_id"], r["endpoint"]),
                                    name="pt-autoscale-stats")
                   for r in view]
            for t in ths:
                t.start()
            for t in ths:
                t.join(timeout=10.0)
        elif view:
            r = view[0]
            snaps[r["replica_id"]] = self._stats_fn(r["endpoint"])
        for r in view:
            in_view.add(r["replica_id"])
            snap = snaps.get(r["replica_id"])
            if not snap:
                continue            # failed pull: baseline left untouched
            g = snap.get("gauges", {})
            c = snap.get("counters", {})
            queue_depth = max(queue_depth,
                              float(g.get("engine.queue_depth") or 0))
            degradation = max(degradation,
                              float(g.get("engine.degradation_level") or 0))
            cur = float(c.get("engine.shed", 0))
            prev = self._last_shed.get(r["replica_id"], cur)
            shed_delta += max(0.0, cur - prev)
            self._last_shed[r["replica_id"]] = cur
        for rid in [k for k in self._last_shed if k not in in_view]:
            del self._last_shed[rid]    # departed replicas age out
        # pending-drain replicas left rotation but the launcher has not
        # confirmed them gone: still paid-for capacity, so they count
        # toward the total the UP clamp bounds — same rationale as
        # breaker-open entries (decide() docstring)
        return {"n": len(view),
                "n_total": len(full) + len(self._pending_drain),
                "outstanding": outstanding, "queue_depth": queue_depth,
                "degradation": degradation, "shed_delta": shed_delta}

    # ------------------------------------------------------------ deciding

    def decide(self, sig: dict) -> str | None:
        """Hysteresis + cooldown gate over one observation; returns
        ``"up"``/``"down"``/None. Pure bookkeeping — no IO — so chaos
        tests feed synthetic signals and assert the exact transitions."""
        p = self.policy
        n = max(1, int(sig["n"]))
        # the UP clamp bounds the TOTAL fleet — every rotation entry,
        # breaker-open ones included (an open breaker is a replica the
        # operator still pays for; spawning "around" it would exceed
        # max_replicas the moment the probe re-closes it). The DOWN clamp
        # stays on the HEALTHY count: draining the last healthy replica
        # because a broken one pads the total would be an outage — and
        # healthy > min implies total > min, so the cost floor holds too.
        # Pressure per replica likewise divides by the healthy count: the
        # capacity actually absorbing the load.
        n_total = int(sig.get("n_total", sig["n"]))
        per = sig["outstanding"] / n
        pressured = (per >= p.up_outstanding_per_replica
                     or sig["queue_depth"] >= p.up_queue_depth
                     or sig["shed_delta"] > 0
                     or sig["degradation"] >= 2)
        idle = (per <= p.down_outstanding_per_replica
                and sig["queue_depth"] == 0 and sig["shed_delta"] == 0
                and sig["degradation"] == 0)
        self._up_votes = self._up_votes + 1 if pressured else 0
        self._down_votes = self._down_votes + 1 if idle else 0
        now = time.monotonic()
        if pressured and n_total < p.max_replicas \
                and self._up_votes >= p.hysteresis_ticks \
                and now - self._last_action_t >= p.up_cooldown_s:
            return "up"
        if idle and sig["n"] > p.min_replicas \
                and self._down_votes >= p.hysteresis_ticks \
                and now - self._last_action_t >= p.down_cooldown_s:
            return "down"
        return None

    # -------------------------------------------------------------- acting

    def scale_up(self) -> str | None:
        """Spawn one replica through the launcher and put it in rotation.
        Returns the new replica id (None if the launcher declined or the
        fleet is already at ``max_replicas`` — this is public API, so the
        spend clamp holds here too, counting every rotation entry plus
        pending drains exactly like decide()'s ``n_total``)."""
        if len(self._router.replica_view()) + len(self._pending_drain) \
                >= self.policy.max_replicas:
            return None
        spawned = self._launcher.spawn()
        if spawned is None:
            return None
        rid, endpoint = spawned
        rid, endpoint = str(rid), str(endpoint)
        self._owned[rid] = endpoint
        for router in self._routers:
            router.add_static_replica(rid, endpoint)
        self._last_action_t = time.monotonic()
        self._up_votes = self._down_votes = 0
        self._m_ups.inc()
        flight.record("autoscaler.scale_up", replica=rid,
                      endpoint=endpoint)
        return rid

    def scale_down(self) -> str | None:
        """Retire one LAUNCHER-OWNED replica: out of rotation first (no
        new traffic lands on it mid-drain), then drain WITH live
        migration to the surviving replicas. Only owned replicas are
        candidates — the controller never kills capacity it didn't
        create (the seed fleet is the operator's). Returns the retired
        replica id (None when nothing was eligible). A drain the
        launcher FAILS (raised — e.g. a pod-delete API timeout) counts
        ``autoscaler.errors``, not ``scale_downs``, and parks the
        replica for retry every tick: it is already out of rotation, but
        the operator keeps paying for it until the launcher confirms it
        is gone."""
        view = self._router.replica_view()
        healthy = [r for r in view if r["breaker"] == "closed"]
        owned = [r for r in healthy if r["replica_id"] in self._owned]
        # the guard counts HEALTHY replicas, mirroring decide()'s down
        # clamp: a breaker-open corpse padding the rotation must never
        # argue for draining the last replica actually serving (this is
        # public API — callers may bypass decide())
        if not owned or len(healthy) <= self.policy.min_replicas:
            return None
        victim = min(owned, key=lambda r: (r["outstanding"],
                                           r["replica_id"]))
        rid = victim["replica_id"]
        for router in self._routers:
            router.remove_static_replica(rid)
        self._last_action_t = time.monotonic()
        self._up_votes = self._down_votes = 0
        self._drain_owned(rid)
        return rid

    def _drain_owned(self, rid: str) -> bool:
        """One launcher drain attempt for an owned, out-of-rotation
        replica; the surviving breaker-closed rotation is the migration
        peer set. Success (clean or not) releases ownership and counts
        the scale-down; a raise parks the replica in the retry set."""
        endpoint = self._owned[rid]
        # decode-capable peers only: a prefill-tier worker refuses
        # OP_MIGRATE typed (it must never decode — docs/SERVING.md
        # "Disaggregated serving"), so offering one just burns a
        # fallback attempt at the worst moment
        peers = [r["endpoint"] for r in self._router.replica_view()
                 if r["replica_id"] != rid and r["breaker"] == "closed"
                 and r.get("role", "both") != "prefill"]
        try:
            clean = self._launcher.drain(rid, endpoint, peers)
        except Exception:  # noqa: BLE001 — launcher failure must not leak
            self._pending_drain[rid] = endpoint
            self._m_errors.inc()
            flight.record("autoscaler.drain_failed", replica=rid,
                          peers=len(peers))
            return False
        self._owned.pop(rid, None)
        self._pending_drain.pop(rid, None)
        self._m_downs.inc()
        flight.record("autoscaler.scale_down", replica=rid,
                      peers=len(peers), clean=bool(clean))
        return True

    def _reap_crashed(self):
        """Detect and retire OWNED replicas that died on their own: a
        spawned replica whose breaker stays open ``reap_open_ticks``
        consecutive ticks is removed from rotation and handed to the
        launcher to kill — without this, a crashed spawn is never a
        scale-down victim (those are picked healthy) yet counts against
        ``max_replicas`` forever, wedging the fleet below capacity. The
        streak resets the moment the breaker leaves ``open`` (half-open
        probing or a re-close must never lose live capacity)."""
        seen = set()
        for r in self._router.replica_view():
            rid = r["replica_id"]
            if rid not in self._owned:
                continue
            seen.add(rid)
            if r["breaker"] != "open":
                self._open_streak.pop(rid, None)
                continue
            streak = self._open_streak.get(rid, 0) + 1
            self._open_streak[rid] = streak
            if streak >= max(1, int(self.policy.reap_open_ticks)):
                self._open_streak.pop(rid, None)
                for router in self._routers:
                    router.remove_static_replica(rid)
                metrics.counter("autoscaler.reaped").inc()
                flight.record("autoscaler.reap", replica=rid,
                              endpoint=self._owned[rid])
                self._drain_owned(rid)  # launcher confirms the kill;
                #                         a raise parks it for retry
        for rid in [k for k in self._open_streak if k not in seen]:
            del self._open_streak[rid]

    def tick(self) -> str | None:
        """One observe -> decide -> act cycle. Synchronous; the loop
        thread calls this, and tests call it directly. Failed drains
        retry FIRST — an orphaned replica is pure cost — then crashed
        spawns are reaped (`_reap_crashed`)."""
        self._m_ticks.inc()
        for rid in list(self._pending_drain):
            self._drain_owned(rid)
        self._reap_crashed()
        self._g_pending.set(len(self._pending_drain))
        sig = self.observe()
        self._g_replicas.set(sig["n"])
        self._g_pressure.set(sig["outstanding"] / max(1, sig["n"]))
        action = self.decide(sig)
        if action == "up":
            return "up" if self.scale_up() is not None else None
        if action == "down":
            return "down" if self.scale_down() is not None else None
        return None

    # ----------------------------------------------------------- lifecycle

    def start(self):
        """Run `tick()` every ``interval_s`` on a daemon thread. The loop
        survives any tick exception (``autoscaler.errors``) — a flaky
        STATS pull or a failed spawn must not end autoscaling forever."""
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="pt-autoscaler")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self._interval):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — control loop must survive
                self._m_errors.inc()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._interval + 5.0)
            self._thread = None

    def next_replica_id(self, prefix: str = "as") -> str:
        """Convenience for launchers: monotonically unique replica ids
        (``as-1``, ``as-2``, ...) that never collide with a registry
        lease."""
        self._spawn_seq += 1
        return f"{prefix}-{self._spawn_seq}"
