"""Disaggregated serving: the prefill-tier primitives.

One replica running both phases means a long prefill stalls every
in-flight decode, and a fleet-shared system prompt is re-prefilled once
per replica. This module holds the pieces that split the phases across
REPLICAS (docs/SERVING.md "Disaggregated serving"; the multi-program
control-plane shape follows the MPMD coordination paper,
arxiv 2412.14374):

- **The ``PTKS1`` page-stream wire format** — the one-shot ``PTKV1``
  KV-handoff blob (`inference/engine.py` KVHandoff) extended into an
  INCREMENTAL record stream: one header record (prompt + cache
  geometry), then per-chunk page batches as the prefill worker's chunked
  prefill produces them, then a final record carrying the seed token and
  the tail pages. Every record carries the PR-12 blake2b body checksum
  (`engine._read_blob_head` discipline): a truncated or bit-flipped
  record is a typed :class:`HandoffCorrupt` refusal BEFORE any page is
  adopted. The stream exists so the wire transfer overlaps the prefill
  compute — the decode replica admits the slot and starts the moment the
  final record lands, not a full blob-serialization later.
- **`KVStreamAssembler`** — the receive side: feed records in order, get
  the assembled :class:`KVHandoff` back on the final record. Assembly is
  HOST-side numpy only — no engine pages are allocated until the
  complete, checksum-verified handoff goes through ``submit_import`` —
  so a partially received stream leaves the decode pool at baseline.
  Legacy one-shot ``PTKV1`` blobs still import: a single PTKV1 record is
  a complete stream.
- **`PrefixDirectory`** — the fleet-wide prefix map the router keeps:
  rolling page hash -> the prefill replica whose engine store holds that
  page (populated from the replicas' STATS prefix exports and from the
  router's own routing decisions, bounded LRU, invalidated on replica
  eviction/refresh/membership churn). Shared-prefix traffic routes with
  cache affinity, so a system prompt is prefilled once per FLEET and
  every later request prefills only its uncached tail.
- **`prompt_page_hashes`** — the engines' rolling full-page prompt hash
  (`DecodeEngine._page_hashes` delegates here), exposed so the router
  can key the directory without asking an engine. Chained hashes mean a
  replica holding page i's hash holds every page before it too — a
  directory lookup walks the hashes longest-first.

The prefill COMPUTE feeding this stream is registry-routed
(`kernels/registry.py`, r15): every chunk the stream exports runs
`models/gpt.py::prefill_chunk_step`, whose attention dispatches between
the XLA gather arm and the authored Pallas ragged prefill kernel
(`kernels/pallas/prefill_attention.py`) under ``FLAGS_tpu_prefill_impl``
— a prefill-worker tier that runs NOTHING ELSE gets the length-scaled
kernel with zero changes here, and `kernel.dispatch.prefill_attention.*`
counts which arm each worker compiled (tests/test_prefill_pallas.py pins
stream-path token identity between arms).
"""
from __future__ import annotations

import hashlib
import json
import struct
import threading
from collections import OrderedDict

import numpy as np

from paddle_tpu.inference.engine import (KVHandoff, _blob_digest,
                                         _read_blob_head)
from paddle_tpu.inference.errors import HandoffCorrupt

__all__ = ["STREAM_MAGIC", "pack_stream_header", "pack_stream_pages",
           "pack_stream_final", "stream_records", "KVStreamAssembler",
           "PrefixDirectory", "prompt_page_hashes"]

STREAM_MAGIC = b"PTKS1\n"

_PREFIX_SEED = b"pt-prefix-v1"


def prompt_page_hashes(ids, page_size: int) -> list[bytes]:
    """Rolling hash over a prompt's FULL token pages: ``h_i = H(h_{i-1} |
    page_i tokens)`` — the ONE hash implementation both the engines'
    prefix stores and the router's fleet directory key on (a drift
    between the two would silently kill every affinity hit). Chained
    keys mean a page is only reusable when every page before it matches
    too."""
    ids = np.ascontiguousarray(np.asarray(ids).reshape(-1), np.int32)
    ps = int(page_size)
    out, h = [], _PREFIX_SEED
    for i in range(ids.size // ps):
        h = hashlib.blake2b(h + ids[i * ps:(i + 1) * ps].tobytes(),
                            digest_size=16).digest()
        out.append(h)
    return out


# ------------------------------------------------------------ wire records


def _pack_record(head: dict, body: bytes) -> bytes:
    head = dict(head)
    head["sum"] = _blob_digest(body)
    hb = json.dumps(head).encode()
    return b"".join([STREAM_MAGIC, struct.pack("<I", len(hb)), hb, body])


def pack_stream_header(seq: int, prompt: np.ndarray, page_size: int,
                       dtype: str, geom, n_pages: int, n_records: int,
                       scales: bool, trace_ctx=None) -> bytes:
    """Record 0 of a KV page stream: the handoff's prompt (body) plus
    everything the assembler needs to preallocate — ``geom`` is
    ``[nl, page_size, nh, dh]``, ``n_pages`` the total page count the
    stream will deliver, ``scales`` whether page batches carry int8
    scale sections. ``trace_ctx`` is an optional ``(trace_id, parent)``
    hex pair: the fleet trace context rides the header so the decode
    side's spans join the same stitched trace even when the relayed
    options carried none (docs/OBSERVABILITY.md "Fleet tracing")."""
    head = {"kind": "head", "seq": int(seq), "page_size": int(page_size),
            "dtype": str(dtype), "prompt_len": int(np.asarray(prompt).size),
            "geom": [int(d) for d in geom], "n_pages": int(n_pages),
            "n_records": int(n_records), "scales": bool(scales)}
    if trace_ctx and trace_ctx[0]:
        head["trace"] = trace_ctx[0]
        if trace_ctx[1]:
            head["parent"] = trace_ctx[1]
    body = np.ascontiguousarray(prompt, np.int32).tobytes()
    return _pack_record(head, body)


def _pages_body(k_blob, v_blob, k_s=None, v_s=None) -> bytes:
    parts = [np.ascontiguousarray(k_blob).tobytes(),
             np.ascontiguousarray(v_blob).tobytes()]
    if k_s is not None:
        parts += [np.ascontiguousarray(k_s, np.float32).tobytes(),
                  np.ascontiguousarray(v_s, np.float32).tobytes()]
    return b"".join(parts)


def pack_stream_pages(seq: int, page0: int, k_blob, v_blob,
                      k_s=None, v_s=None) -> bytes:
    """One page batch: blobs are ``[nl, n, page_size, nh, dh]`` (scales
    ``[nl, n, page_size, nh]`` f32, int8 pools only), landing at page
    indices ``[page0, page0 + n)`` of the stream's page list."""
    n = int(np.asarray(k_blob).shape[1])
    head = {"kind": "pages", "seq": int(seq), "page0": int(page0), "n": n}
    return _pack_record(head, _pages_body(k_blob, v_blob, k_s, v_s))


def pack_stream_final(seq: int, first_token: int, page0: int, k_blob,
                      v_blob, k_s=None, v_s=None) -> bytes:
    """The closing record: the prefill's sampled seed token plus the tail
    page batch (``n`` may be 0 — a prompt ending on a page boundary has
    no tail). The decode side admits the slot the moment this lands."""
    n = int(np.asarray(k_blob).shape[1])
    head = {"kind": "final", "seq": int(seq),
            "first_token": int(first_token), "page0": int(page0), "n": n}
    return _pack_record(head, _pages_body(k_blob, v_blob, k_s, v_s))


def stream_records(handoff: KVHandoff, pages_per_batch: int = 1) \
        -> list[bytes]:
    """Split a one-shot :class:`KVHandoff` into PTKS1 stream records —
    the bridge for tests/drills and for re-streaming a blob that arrived
    one-shot. The engine's live export path packs records directly as
    its chunks complete (`DecodeEngine.submit_prefill_stream`)."""
    ppb = max(1, int(pages_per_batch))
    nl, n_pages, ps, nh, dh = handoff.k_pages.shape
    scales = handoff.k_scales is not None
    starts = list(range(0, n_pages, ppb))
    if starts:
        tail0 = starts.pop()         # the last batch rides the final record
    else:
        tail0 = 0
    n_records = 2 + len(starts)
    recs = [pack_stream_header(0, handoff.prompt, handoff.page_size,
                               handoff.cache_dtype, [nl, ps, nh, dh],
                               n_pages, n_records, scales)]
    for i, p0 in enumerate(starts):
        sl = slice(p0, min(p0 + ppb, n_pages))
        recs.append(pack_stream_pages(
            1 + i, p0, handoff.k_pages[:, sl], handoff.v_pages[:, sl],
            handoff.k_scales[:, sl] if scales else None,
            handoff.v_scales[:, sl] if scales else None))
    sl = slice(tail0, n_pages)
    recs.append(pack_stream_final(
        n_records - 1, handoff.first_token, tail0,
        handoff.k_pages[:, sl], handoff.v_pages[:, sl],
        handoff.k_scales[:, sl] if scales else None,
        handoff.v_scales[:, sl] if scales else None))
    return recs


def _np_cache_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


class KVStreamAssembler:
    """Receive side of a PTKS1 page stream: ``feed`` records in order;
    the final record returns the assembled :class:`KVHandoff` (None
    until then). Everything is host-side numpy — no engine resource is
    touched until the complete handoff is imported, so abandoning a
    partial stream costs nothing and a damaged record refuses typed
    (:class:`HandoffCorrupt`, checksum-verified before any byte of the
    payload is interpreted) before any page could be adopted.

    A single legacy one-shot ``PTKV1`` blob is accepted as a complete
    stream — old senders keep working unchanged."""

    def __init__(self):
        self._seq = 0
        self._head: dict | None = None
        self._k = self._v = self._ks = self._vs = None
        self._prompt: np.ndarray | None = None
        self._covered: np.ndarray | None = None
        self.complete = False
        # fleet trace context carried by the stream header, if any:
        # (trace_id, parent) hex pair the receiving replica attaches to
        # its RequestTrace when the relayed options carried none
        self.trace_ctx = None

    def _corrupt(self, msg: str):
        raise HandoffCorrupt(f"KV stream: {msg}")

    def feed(self, buf: bytes) -> KVHandoff | None:
        if self.complete:
            self._corrupt("record after the final record")
        if buf[:len(KVHandoff.MAGIC)] == KVHandoff.MAGIC:
            # legacy one-shot PTKV1 blob = a complete stream of one
            if self._seq != 0:
                self._corrupt("one-shot PTKV1 blob mid-stream")
            self.complete = True
            return KVHandoff.unpack(buf)
        if buf[:len(STREAM_MAGIC)] != STREAM_MAGIC:
            self._corrupt("bad record magic (not PTKS1/PTKV1)")
        # _read_blob_head verifies the blake2b body checksum FIRST — a
        # truncated or bit-flipped record dies here, typed
        head, off = _read_blob_head(buf, len(STREAM_MAGIC),
                                    "PTKS1 stream record")
        # the body checksum does not cover the JSON header, so every
        # header FIELD read below must refuse typed on damage too —
        # never escape as a raw TypeError/ValueError
        try:
            seq = int(head.get("seq", -1))
        except (TypeError, ValueError):
            seq = -1
        if seq != self._seq:
            self._corrupt(f"record out of order: got seq "
                          f"{head.get('seq')}, want {self._seq}")
        kind = head.get("kind")
        if self._seq == 0:
            if kind != "head":
                self._corrupt(f"first record is {kind!r}, not the header")
            self._start(head, buf, off)
            self._seq += 1
            return None
        if self._head is None:
            self._corrupt("page record before the header")
        if kind not in ("pages", "final"):
            self._corrupt(f"unknown record kind {kind!r}")
        self._place(head, buf, off)
        self._seq += 1
        if kind != "final":
            return None
        if int(self._head["n_records"]) != self._seq:
            self._corrupt(f"final record at seq {self._seq - 1} but the "
                          f"header promised {self._head['n_records']} "
                          f"records")
        if not bool(self._covered.all()):
            missing = int((~self._covered).sum())
            self._corrupt(f"final record landed with {missing} page(s) "
                          f"never delivered")
        try:
            first_token = int(head["first_token"])
        except (KeyError, TypeError, ValueError):
            self._corrupt("final record carries no usable first_token")
        self.complete = True
        return KVHandoff(
            prompt=self._prompt, first_token=first_token,
            k_pages=self._k, v_pages=self._v,
            page_size=int(self._head["page_size"]),
            cache_dtype=str(self._head["dtype"]),
            k_scales=self._ks, v_scales=self._vs)

    def _start(self, head: dict, buf: bytes, off: int):
        try:
            nl, ps, nh, dh = (int(d) for d in head["geom"])
            n_pages = int(head["n_pages"])
            s0 = int(head["prompt_len"])
            page_size = int(head["page_size"])
            n_records = int(head["n_records"])
            dt = _np_cache_dtype(str(head["dtype"]))
            bad_geom = min(nl, ps, nh, dh, page_size, n_records) < 1 \
                or n_pages < 1 or s0 < 1 \
                or n_pages != -(-s0 // page_size)
        except (KeyError, ValueError, TypeError,
                ZeroDivisionError) as e:
            self._corrupt(f"header unusable ({type(e).__name__}: {e})")
        if bad_geom:
            self._corrupt(f"header geometry inconsistent: {n_pages} pages "
                          f"for a {s0}-token prompt at page_size "
                          f"{head['page_size']}")
        self._prompt = np.frombuffer(buf, np.int32, count=s0,
                                     offset=off).copy()
        self._k = np.zeros((nl, n_pages, ps, nh, dh), dt)
        self._v = np.zeros_like(self._k)
        if bool(head.get("scales")):
            self._ks = np.zeros((nl, n_pages, ps, nh), np.float32)
            self._vs = np.zeros_like(self._ks)
        self._covered = np.zeros(n_pages, bool)
        self._head = head
        if head.get("trace"):
            self.trace_ctx = (str(head["trace"]), head.get("parent"))

    def _place(self, head: dict, buf: bytes, off: int):
        try:
            p0, n = int(head.get("page0", -1)), int(head.get("n", -1))
        except (TypeError, ValueError):
            p0 = n = -1
        n_pages = self._k.shape[1]
        if p0 < 0 or n < 0 or p0 + n > n_pages:
            self._corrupt(f"page batch [{p0}, {p0 + n}) outside the "
                          f"stream's {n_pages} pages")
        if n and bool(self._covered[p0:p0 + n].any()):
            self._corrupt(f"page batch [{p0}, {p0 + n}) overlaps pages "
                          f"already delivered")
        nl, _, ps, nh, dh = self._k.shape
        shape = (nl, n, ps, nh, dh)
        cnt = int(np.prod(shape))
        dt = self._k.dtype
        want = 2 * cnt * dt.itemsize
        sshape = (nl, n, ps, nh)
        scnt = int(np.prod(sshape))
        if self._ks is not None:
            want += 2 * scnt * 4
        if len(buf) - off != want:
            self._corrupt(f"page batch body is {len(buf) - off} bytes, "
                          f"want {want} for {n} page(s)")
        if n == 0:
            return
        k = np.frombuffer(buf, dt, count=cnt, offset=off).reshape(shape)
        off += cnt * dt.itemsize
        v = np.frombuffer(buf, dt, count=cnt, offset=off).reshape(shape)
        off += cnt * dt.itemsize
        self._k[:, p0:p0 + n] = k
        self._v[:, p0:p0 + n] = v
        if self._ks is not None:
            ks = np.frombuffer(buf, np.float32, count=scnt,
                               offset=off).reshape(sshape)
            off += scnt * 4
            vs = np.frombuffer(buf, np.float32, count=scnt,
                               offset=off).reshape(sshape)
            self._ks[:, p0:p0 + n] = ks
            self._vs[:, p0:p0 + n] = vs
        self._covered[p0:p0 + n] = True


# -------------------------------------------------------- fleet directory


class PrefixDirectory:
    """The router's fleet-wide prefix map: rolling page hash -> the
    prefill replica whose engine store holds that page. Bounded LRU
    (``capacity`` hashes), thread-safe; entries leave on replica
    departure (`invalidate`), on the replica's own store shrinking
    (`replace`, driven by the STATS prefix export — evictions and
    weight-refresh flushes propagate here), and by LRU pressure.

    Lookups walk the prompt's hashes LONGEST-first: the hashes are
    chained (`prompt_page_hashes`), so a replica holding page i holds
    every page before it — the first hit names both the replica and the
    cached depth.

    KV tiering (docs/SERVING.md "KV tiering"): replicas advertise their
    SPILLED pages (host/disk tiers) alongside the resident ones, so a
    directory hit on a spilled prefix still routes to the one replica
    that can re-upload it instead of re-prefilling anywhere. The
    directory tracks which hashes are spilled per replica —
    `is_spilled` / `spilled_depth` let the router meter how much of its
    affinity traffic rides the spill tiers."""

    def __init__(self, capacity: int = 4096):
        self._cap = max(1, int(capacity))
        self._lock = threading.Lock()
        self._map: OrderedDict[bytes, str] = OrderedDict()
        self._by_replica: dict[str, set[bytes]] = {}
        self._spilled: dict[str, set[bytes]] = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def _drop(self, h: bytes):
        rid = self._map.pop(h, None)
        if rid is not None:
            s = self._by_replica.get(rid)
            if s is not None:
                s.discard(h)
                if not s:
                    del self._by_replica[rid]
            sp = self._spilled.get(rid)
            if sp is not None:
                sp.discard(h)
                if not sp:
                    del self._spilled[rid]

    def register(self, hashes, replica_id: str):
        """Record that ``replica_id``'s store holds these pages (the
        router just routed the prompt there, or STATS said so). Last
        writer wins — the directory is best-effort routing state, not
        ownership."""
        rid = str(replica_id)
        with self._lock:
            for h in hashes:
                h = bytes(h)
                self._drop(h)
                self._map[h] = rid
                self._by_replica.setdefault(rid, set()).add(h)
            while len(self._map) > self._cap:
                self._drop(next(iter(self._map)))

    def replace(self, replica_id: str, hashes, spilled=()):
        """Reconcile with the replica's OWN prefix export (STATS): drop
        directory entries the replica no longer holds (evicted past its
        tiers, flushed on a weight refresh), add the ones it does.
        ``spilled`` marks the subset that lives in the replica's
        host/disk spill tiers rather than HBM — routable all the same
        (the replica re-uploads on hit), but metered separately."""
        rid = str(replica_id)
        keep = {bytes(h) for h in hashes}
        with self._lock:
            stale = [h for h in self._by_replica.get(rid, ()) if h not in
                     keep]
            for h in stale:
                self._drop(h)
            sp = {bytes(h) for h in spilled} & keep
            if sp:
                self._spilled[rid] = sp
            else:
                self._spilled.pop(rid, None)
        self.register(keep, rid)

    def invalidate(self, replica_id: str):
        """Membership churn: the replica left the rotation — every entry
        pointing at it is dead weight."""
        rid = str(replica_id)
        with self._lock:
            for h in list(self._by_replica.get(rid, ())):
                self._drop(h)
            self._spilled.pop(rid, None)

    def is_spilled(self, h, replica_id: str) -> bool:
        """True when the replica advertised this hash from a SPILL tier —
        an affinity route to it re-uploads instead of reading HBM."""
        with self._lock:
            return bytes(h) in self._spilled.get(str(replica_id), ())

    def spilled_depth(self, replica_id: str) -> int:
        """How many of the replica's advertised pages are spilled — the
        capacity dashboards' view of each replica's tier economy."""
        with self._lock:
            return len(self._spilled.get(str(replica_id), ()))

    def lookup(self, hashes) -> tuple[str | None, int]:
        """``(replica_id, cached_pages)`` for the LONGEST prefix any
        replica holds, or ``(None, 0)``. The caller re-validates the
        replica against live membership/breaker state — the directory
        never blocks a route, it only biases one."""
        with self._lock:
            for i in range(len(hashes) - 1, -1, -1):
                rid = self._map.get(bytes(hashes[i]))
                if rid is not None:
                    return rid, i + 1
        return None, 0
