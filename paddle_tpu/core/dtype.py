"""Dtype system.

Paddle-flavored dtype names mapped onto JAX/XLA dtypes. The reference keeps an enum
``DataType`` (`paddle/phi/common/data_type.h`) plus float16/bfloat16 value types
(`paddle/fluid/platform/bfloat16.h`); on TPU the value types are native XLA types, so this
module only needs the name <-> numpy-dtype mapping and the default-dtype state
(reference: `python/paddle/framework/framework.py` set_default_dtype).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

# Canonical name -> numpy dtype object. bfloat16 is first-class on TPU.
_NAME_TO_DTYPE = {
    "bool": np.dtype(np.bool_),
    "uint8": np.dtype(np.uint8),
    "int8": np.dtype(np.int8),
    "int16": np.dtype(np.int16),
    "int32": np.dtype(np.int32),
    "int64": np.dtype(np.int64),
    "float16": np.dtype(np.float16),
    "bfloat16": np.dtype(ml_dtypes.bfloat16),
    "float32": np.dtype(np.float32),
    "float64": np.dtype(np.float64),
    "complex64": np.dtype(np.complex64),
    "complex128": np.dtype(np.complex128),
    "float8_e4m3fn": np.dtype(ml_dtypes.float8_e4m3fn),
    "float8_e5m2": np.dtype(ml_dtypes.float8_e5m2),
}

_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bf16": "bfloat16",
    "fp16": "float16",
    "fp32": "float32",
    "fp64": "float64",
}

bool_ = _NAME_TO_DTYPE["bool"]
uint8 = _NAME_TO_DTYPE["uint8"]
int8 = _NAME_TO_DTYPE["int8"]
int16 = _NAME_TO_DTYPE["int16"]
int32 = _NAME_TO_DTYPE["int32"]
int64 = _NAME_TO_DTYPE["int64"]
float16 = _NAME_TO_DTYPE["float16"]
bfloat16 = _NAME_TO_DTYPE["bfloat16"]
float32 = _NAME_TO_DTYPE["float32"]
float64 = _NAME_TO_DTYPE["float64"]
complex64 = _NAME_TO_DTYPE["complex64"]
complex128 = _NAME_TO_DTYPE["complex128"]

_default_dtype = float32


def set_default_dtype(d):
    global _default_dtype
    d = convert_dtype(d)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(f"default dtype must be a floating dtype, got {d}")
    _default_dtype = d


def get_default_dtype() -> np.dtype:
    return _default_dtype


def convert_dtype(d) -> np.dtype:
    """Normalize any dtype spec (str / numpy / jax / Tensor dtype) to a numpy dtype."""
    if d is None:
        return _default_dtype
    if isinstance(d, np.dtype):
        return d
    if isinstance(d, str):
        name = _ALIASES.get(d, d)
        if name in _NAME_TO_DTYPE:
            return _NAME_TO_DTYPE[name]
        return np.dtype(name)
    if d in (float,):
        return _default_dtype
    if d in (int,):
        return int64
    if d in (bool,):
        return bool_
    if d in (complex,):
        return complex64
    # numpy scalar types, jnp.float32 etc.
    return np.dtype(d)


def dtype_name(d) -> str:
    d = convert_dtype(d)
    return d.name


def is_floating(d) -> bool:
    d = convert_dtype(d)
    return jnp.issubdtype(d, jnp.floating)


def is_integer(d) -> bool:
    d = convert_dtype(d)
    return jnp.issubdtype(d, jnp.integer)


def is_complex(d) -> bool:
    d = convert_dtype(d)
    return jnp.issubdtype(d, jnp.complexfloating)


def finfo(d):
    return jnp.finfo(convert_dtype(d))


def iinfo(d):
    return jnp.iinfo(convert_dtype(d))
