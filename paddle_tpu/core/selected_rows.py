"""SelectedRows — row-sparse gradient container.

Rebuild of the reference's `phi::SelectedRows`
(`paddle/phi/core/selected_rows.h`): a (rows, values, height) triple used for
embedding-table gradients so an update touches only the looked-up rows. The
reference threads it through sparse kernels (`phi/kernels/selected_rows/`);
here the optimizers dispatch on the grad type and apply row-wise scatter
updates (`w.at[rows]`), which XLA lowers to an efficient scatter on TPU.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp


class SelectedRows:
    """rows: int array [N]; values: array [N, ...]; height: size of dim 0 of
    the dense tensor this sparsely represents."""

    def __init__(self, rows, values, height):
        self.rows = jnp.asarray(rows).reshape(-1)
        self.values = jnp.asarray(values)
        self.height = int(height)
        if self.values.shape[0] != self.rows.shape[0]:
            raise ValueError(
                f"values rows {self.values.shape[0]} != rows {self.rows.shape[0]}")

    @property
    def shape(self):
        return [self.height] + list(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def merge(self) -> "SelectedRows":
        """Sum duplicate rows (ref `merge_selected_rows` op,
        `phi/kernels/selected_rows/merge_selected_rows_kernel.h`). Eager-only
        (unique is data-dependent)."""
        rows_np = np.asarray(self.rows)
        uniq, inv = np.unique(rows_np, return_inverse=True)
        import jax
        summed = jax.ops.segment_sum(self.values, jnp.asarray(inv),
                                     num_segments=len(uniq))
        return SelectedRows(jnp.asarray(uniq), summed, self.height)

    def to_dense(self):
        out = jnp.zeros((self.height,) + tuple(self.values.shape[1:]),
                        self.values.dtype)
        return out.at[self.rows].add(self.values)

    def numpy(self):
        return np.asarray(self.to_dense())

    def accumulate(self, other: "SelectedRows") -> "SelectedRows":
        """Concatenate contributions (grad accumulation across micro-steps)."""
        if other.height != self.height:
            raise ValueError("height mismatch in SelectedRows accumulation")
        return SelectedRows(jnp.concatenate([self.rows, other.rows]),
                            jnp.concatenate([self.values, other.values]),
                            self.height)

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, nnz_rows="
                f"{self.rows.shape[0]}, value_shape={tuple(self.values.shape)})")


def merge_selected_rows(x):
    """Functional form of SelectedRows.merge (ref `merge_selected_rows` op)."""
    if not isinstance(x, SelectedRows):
        raise TypeError("merge_selected_rows expects a SelectedRows")
    return x.merge()
