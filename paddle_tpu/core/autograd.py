"""Imperative autograd on a functional substrate.

The reference implements dygraph autograd as generated per-op GradNode classes plus a
ready-queue backward engine (`paddle/fluid/eager/grad_node_info.h:168`,
`paddle/fluid/eager/backward.cc:105`). Here the same user-facing contract
(``Tensor.backward()`` accumulating ``.grad`` on leaves, hooks, ``retain_graph``,
``no_grad``) is built as a *tape of jax.vjp closures*:

- every op executed through :func:`apply` calls ``jax.vjp`` when gradients are required,
  storing the vjp closure in a :class:`GradNode`;
- ``backward()`` walks reachable nodes in reverse creation order (creation order is a
  valid topological order, so all consumers of a tensor are processed before its
  producing node — the same invariant the reference's in-degree map establishes at
  `backward.cc:22`);
- because ``jax.vjp`` works on tracers, this exact machinery also runs *inside*
  ``jax.jit``: tracing a train step that calls ``loss.backward()`` dissolves the tape
  into one XLA computation (the TPU-native analog of the reference's ``run_program`` op,
  `paddle/fluid/operators/run_program_op.cc`).
"""
from __future__ import annotations

import contextlib
import functools
import itertools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

_node_counter = itertools.count()

# ---------------------------------------------------------------------------- grad mode

_grad_enabled: bool = True


def is_grad_enabled() -> bool:
    return _grad_enabled


@contextlib.contextmanager
def set_grad_enabled(mode: bool):
    global _grad_enabled
    prev = _grad_enabled
    _grad_enabled = bool(mode)
    try:
        yield
    finally:
        _grad_enabled = prev


class no_grad(contextlib.ContextDecorator):
    """Context manager / decorator disabling gradient recording (ref: paddle.no_grad)."""

    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = False
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = True
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False


# ---------------------------------------------------------------------------- GradNode


class GradNode:
    """One recorded op application: holds the vjp closure and graph edges.

    Mirrors ``egr::GradNodeBase`` + ``Edge`` (`eager/grad_node_info.h:168,50`), except the
    backward computation is the jax.vjp closure rather than a generated kernel call.
    """

    __slots__ = (
        "vjp_fn", "prim", "inputs", "out_avals", "out_refs", "index", "name",
        "released", "multi", "__weakref__",
    )

    def __init__(self, vjp_fn, inputs, out_avals, name="", prim=None, multi=False):
        self.vjp_fn = vjp_fn
        self.prim = prim                # primal fn (kwargs bound) for create_graph
        self.multi = multi              # primal returned a tuple (vjp wants tuple ct)
        self.inputs = inputs            # list[Tensor] — strong refs (like TensorWrapper)
        self.out_avals = out_avals      # list[(shape, dtype)]
        self.out_refs = []              # list[weakref to output Tensors] for hooks
        self.index = next(_node_counter)
        self.name = name
        self.released = False

    def release(self):
        self.vjp_fn = None
        self.prim = None
        self.inputs = ()
        self.out_refs = ()
        self.released = True

    def __repr__(self):
        return f"<GradNode {self.name}#{self.index}{' released' if self.released else ''}>"


def _tensor_mod():
    from paddle_tpu.core import tensor as T
    return T


def _needs_grad(t) -> bool:
    return (not t.stop_gradient) and jnp.issubdtype(t.dtype, jnp.inexact)


def _x64_off_scope():
    if jax.config.jax_enable_x64:
        # jax.enable_x64(False) was removed upstream; the experimental
        # context manager is the surviving spelling of a scoped x64-off
        from jax.experimental import disable_x64
        return disable_x64()
    import contextlib
    return contextlib.nullcontext()


def apply(prim: Callable, *inputs, op_name: str = "", n_outputs: int | None = None,
          x64_off: bool = False, **static_kwargs):
    """Execute ``prim(*arrays, **static_kwargs)`` with autograd recording.

    ``prim`` must be a pure jax function of the positional arrays. Returns Tensor or
    tuple of Tensors. The single dispatch point — the analog of the generated
    ``*_ad_func`` forwards (`eager/auto_code_generator/generator/eager_gen.py`).

    ``x64_off``: trace this op's forward AND backward under x64-disabled dtype
    promotion — required by Pallas kernels (splash/flash attention) that mix
    int32 iota with weak python ints, which breaks under paddle's global
    jax_enable_x64. The backward scope matters because vjp_fn traces the
    custom-vjp bwd rule at backward time, long after the forward scope exits.
    """
    T = _tensor_mod()
    arrays = [t._read() for t in inputs]
    record = _grad_enabled and any(_needs_grad(t) for t in inputs)
    fn = functools.partial(prim, **static_kwargs) if static_kwargs else prim
    if x64_off:
        inner = fn

        def fn(*a):
            with _x64_off_scope():
                return inner(*a)

    if not record:
        out = fn(*arrays)
        if _DEBUG_CHECKS:
            _debug_check_outputs(
                op_name or getattr(prim, "__name__", "op"),
                list(out) if isinstance(out, (tuple, list)) else [out])
        return _wrap_outputs(out, node=None, stop_gradient=True)

    out, raw_vjp_fn = jax.vjp(fn, *arrays)
    if x64_off:
        def vjp_fn(cts, _raw=raw_vjp_fn):
            with _x64_off_scope():
                return _raw(cts)
    else:
        vjp_fn = raw_vjp_fn
    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    node = GradNode(
        vjp_fn, list(inputs), [(o.shape, o.dtype) for o in outs],
        name=op_name or getattr(prim, "__name__", "op"), prim=fn, multi=multi,
    )
    if _DEBUG_CHECKS:
        _debug_check_outputs(node.name, outs)
    result = _wrap_outputs(out, node=node, stop_gradient=False)
    return result


_DEBUG_CHECKS = False     # flipped by flags.set_flags (check_nan_inf/benchmark)


def _debug_check_outputs(op_name, outs):
    """FLAGS_check_nan_inf / FLAGS_benchmark hooks at the dispatch point (ref
    per-op nan/inf detection `eager/nan_inf_utils.cc`, gated the same way).
    Eager-only: inside a trace, jax_debug_nans (also wired to the flag) covers
    the compiled path."""
    from paddle_tpu.framework.flags import flag_value
    check = flag_value("check_nan_inf")
    bench = flag_value("benchmark")
    if not (check or bench):
        return
    for o in outs:
        if isinstance(o, jax.core.Tracer):
            return
        if bench:
            jax.block_until_ready(o)
        if check and jnp.issubdtype(o.dtype, jnp.inexact):
            bad = ~jnp.isfinite(o)
            if bool(jnp.any(bad)):
                raise FloatingPointError(
                    f"FLAGS_check_nan_inf: op '{op_name}' produced "
                    f"{int(jnp.sum(bad))} non-finite value(s) in an output of "
                    f"shape {tuple(o.shape)}")


def _wrap_outputs(out, node, stop_gradient):
    import weakref
    T = _tensor_mod()
    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    wrapped = []
    for i, o in enumerate(outs):
        t = T.Tensor(o, stop_gradient=stop_gradient, _internal=True)
        if node is not None:
            t._grad_node = node
            t._out_slot = i
            node.out_refs.append(weakref.ref(t))
        wrapped.append(t)
    if multi:
        return tuple(wrapped)
    return wrapped[0]


# ---------------------------------------------------------------------------- backward


def _collect_subgraph(roots: Sequence[GradNode]):
    """DFS the node graph reachable from roots; returns nodes sorted by index desc."""
    seen = {}
    stack = list(roots)
    while stack:
        n = stack.pop()
        if n is None or n.index in seen:
            continue
        if n.released:
            raise RuntimeError(
                f"GradNode {n.name} has been released; set retain_graph=True to "
                "backward through the same graph twice.")
        seen[n.index] = n
        for t in n.inputs:
            if t._grad_node is not None:
                stack.append(t._grad_node)
    return sorted(seen.values(), key=lambda n: -n.index)


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Run backward from ``tensors``, accumulating ``.grad`` on leaf tensors.

    Ref: ``egr::Backward`` (`eager/backward.cc:393`). Leaf accumulation mirrors
    ``GradNodeAccumulation`` (`eager/accumulation/accumulation_node.cc`).
    """
    T = _tensor_mod()
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # node -> {slot: cotangent array}
    pending: dict[int, dict[int, Any]] = {}
    nodes_by_id: dict[int, GradNode] = {}
    roots = []
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            # reference semantics (varbase_patch_methods.py:234): implicit initial
            # gradient is ones for ANY shape, not just scalars
            g_arr = jnp.ones(t.shape, t.dtype)
        else:
            g_arr = g._data if isinstance(g, T.Tensor) else jnp.asarray(g, t.dtype)
        node = t._grad_node
        if node is None:
            if not t.stop_gradient:
                _accumulate_leaf(t, g_arr)
            continue
        roots.append(node)
        slot_map = pending.setdefault(node.index, {})
        prev = slot_map.get(t._out_slot)
        slot_map[t._out_slot] = g_arr if prev is None else prev + g_arr
        nodes_by_id[node.index] = node

    order = _collect_subgraph(roots)
    for node in order:
        slot_map = pending.pop(node.index, {})
        cotangents = []
        for i, (shape, dtype) in enumerate(node.out_avals):
            g = slot_map.get(i)
            if g is None:
                g = jnp.zeros(shape, dtype)
            else:
                g = jnp.asarray(g, dtype)
            cotangents.append(g)
        # fire output-tensor hooks now that cotangents are final
        for ref in node.out_refs:
            t = ref()
            if t is not None and t._hooks:
                g = cotangents[t._out_slot]
                for hook in t._hooks.values():
                    new_g = hook(T.Tensor(g, stop_gradient=True, _internal=True))
                    if new_g is not None:
                        g = new_g._data if isinstance(new_g, T.Tensor) else jnp.asarray(new_g)
                cotangents[t._out_slot] = g
        in_grads = node.vjp_fn(tuple(cotangents) if node.multi
                               else cotangents[0])
        for t, g in zip(node.inputs, in_grads):
            if g is None or g.dtype == jax.dtypes.float0:
                continue
            if t.stop_gradient:
                continue
            child = t._grad_node
            if child is None:
                _accumulate_leaf(t, g)
            else:
                m = pending.setdefault(child.index, {})
                prev = m.get(t._out_slot)
                m[t._out_slot] = g if prev is None else prev + g
        if not retain_graph:
            node.release()


def _accumulate_leaf(t, g_arr):
    T = _tensor_mod()
    g_arr = jnp.asarray(g_arr, t.dtype)
    if t._hooks:
        for hook in t._hooks.values():
            new_g = hook(T.Tensor(g_arr, stop_gradient=True, _internal=True))
            if new_g is not None:
                g_arr = new_g._data if isinstance(new_g, T.Tensor) else jnp.asarray(new_g)
    if t._grad is not None and not isinstance(t._grad, T.Tensor):
        # existing grad is a SelectedRows (sparse embedding + tied dense use):
        # densify so both contributions survive
        t._grad = T.Tensor(t._grad.to_dense().astype(t.dtype),
                           stop_gradient=True, _internal=True)
    if t._grad is None:
        t._grad = T.Tensor(g_arr, stop_gradient=True, _internal=True)
    else:
        t._grad = T.Tensor(t._grad._data + g_arr, stop_gradient=True, _internal=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False,
         only_inputs=True, allow_unused=False, no_grad_vars=None):
    """Functional gradient API (ref: ``paddle.grad``, `eager/general_grad.h`).

    Computes gradients of ``outputs`` w.r.t. ``inputs`` without touching ``.grad``.
    ``create_graph`` re-records backward ops on the tape for higher-order grads.
    """
    T = _tensor_mod()
    single_in = not isinstance(inputs, (list, tuple))
    if single_in:
        inputs = [inputs]
    if not isinstance(outputs, (list, tuple)):
        outputs = [outputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        retain_graph = create_graph

    no_grad_ids = {id(v) for v in (no_grad_vars or [])}
    input_ids = {id(t): i for i, t in enumerate(inputs)}
    results: list = [None] * len(inputs)

    # Cotangent values flow through the walk either as raw arrays (create_graph=False)
    # or as tape-connected Tensors (create_graph=True) so grad-of-grad stays wired.
    if create_graph:
        def _lift(arr):
            return T.Tensor(arr, stop_gradient=True, _internal=True)

        def _vadd(a, b):
            return a + b  # Tensor arithmetic — records on the tape

        def _vdata(v):
            return v._data
    else:
        def _lift(arr):
            return arr

        def _vadd(a, b):
            return a + b

        def _vdata(v):
            return v

    pending: dict[int, dict[int, Any]] = {}
    roots = []
    for t, g in zip(outputs, grad_outputs):
        if g is None:
            gv = _lift(jnp.ones(t.shape, t.dtype))
        elif isinstance(g, T.Tensor):
            gv = g if create_graph else g._data
        else:
            gv = _lift(jnp.asarray(g, t.dtype))
        if id(t) in input_ids:
            i = input_ids[id(t)]
            results[i] = gv if results[i] is None else _vadd(results[i], gv)
        node = t._grad_node
        if node is None:
            continue
        roots.append(node)
        m = pending.setdefault(node.index, {})
        prev = m.get(t._out_slot)
        m[t._out_slot] = gv if prev is None else _vadd(prev, gv)

    order = _collect_subgraph(roots)
    for node in order:
        slot_map = pending.pop(node.index, None)
        if slot_map is None:
            continue  # not on a path from outputs
        cotangents = []
        for i, (shape, dtype) in enumerate(node.out_avals):
            g = slot_map.get(i)
            cotangents.append(_lift(jnp.zeros(shape, dtype)) if g is None else g)
        # float0 cotangents appear exactly for non-inexact primal inputs, so the
        # keep-mask is static and keeps the filtered vjp outputs aligned.
        keeps = [jnp.issubdtype(t.dtype, jnp.inexact) for t in node.inputs]
        if create_graph:
            # Re-derive the vjp from the primal fn applied to the tape Tensors so the
            # grad-of-grad graph connects to the primal inputs (jax.vjp residuals in
            # node.vjp_fn are baked constants and would not be differentiated).
            n_in = len(node.inputs)
            n_out = len(node.out_avals)

            def grad_op(*args, _fn=node.prim, _n_in=n_in, _multi=node.multi,
                        _keeps=tuple(keeps)):
                primals, cts = args[:_n_in], args[_n_in:]
                _, vjp_fn = jax.vjp(_fn, *primals)
                gs = vjp_fn(tuple(cts) if _multi else cts[0])
                return tuple(g for g, k in zip(gs, _keeps) if k)

            grads = apply(grad_op, *node.inputs, *cotangents,
                          op_name=f"{node.name}_grad")
            if not isinstance(grads, tuple):
                grads = (grads,)
            kept = iter(grads)
            in_grads = [next(kept) if k else None for k in keeps]
        else:
            out = node.vjp_fn(tuple(cotangents) if node.multi
                              else cotangents[0])
            in_grads = [g if k else None for g, k in zip(out, keeps)]
        for t, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            if id(t) in no_grad_ids or t.stop_gradient:
                continue
            if id(t) in input_ids:
                i = input_ids[id(t)]
                results[i] = g if results[i] is None else _vadd(results[i], g)
            child = t._grad_node
            if child is not None:
                m = pending.setdefault(child.index, {})
                prev = m.get(t._out_slot)
                m[t._out_slot] = g if prev is None else _vadd(prev, g)
        if not retain_graph and not create_graph:
            node.release()

    out_tensors = []
    for i, (t, r) in enumerate(zip(inputs, results)):
        if r is None:
            if not allow_unused:
                raise RuntimeError(
                    f"input {i} is unreachable from outputs; pass allow_unused=True "
                    "to get None for such inputs")
            out_tensors.append(None)
        elif isinstance(r, T.Tensor):
            out_tensors.append(r)
        else:
            out_tensors.append(T.Tensor(jnp.asarray(r), stop_gradient=True,
                                        _internal=True))
    # ALWAYS a list, matching the reference ("a list of Tensors, whose
    # length is the same as the Tensor number inside inputs") — unwrapping
    # for a single bare-Tensor input made the common `paddle.grad(y, x)[0]`
    # idiom silently index ELEMENT 0 of the gradient instead
    return out_tensors
