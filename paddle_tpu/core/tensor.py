"""The imperative Tensor: a Paddle-flavored wrapper over an immutable ``jax.Array``.

Reference analog: ``phi::DenseTensor`` (`paddle/phi/core/dense_tensor.h:38`) plus the
eager-mode Python Tensor (`paddle/fluid/pybind/eager.cc`, `eager_method.cc`). Because
``jax.Array`` is immutable, "in-place" ops rebind ``_data``; previously recorded vjp
closures keep referencing the old value, so the tape stays consistent without the
reference's inplace-version checks (`paddle/fluid/eager/tensor_wrapper.h`).

The same Tensor object can hold either a concrete device array (eager mode) or a JAX
tracer (inside ``to_static``/``jax.jit`` capture) — this is what collapses the
reference's dygraph/static duality into one code path.
"""
from __future__ import annotations

import itertools

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core import autograd
from paddle_tpu.core import dtype as dtype_mod

# tensor-creation clock: lets jit capture distinguish pre-existing state tensors
# (params, buffers, RNG/optimizer state) from temporaries created during a probe run
_creation_clock = 0


def current_stamp() -> int:
    return _creation_clock


_ops_cache = None


def _ops():
    global _ops_cache
    if _ops_cache is None:
        import paddle_tpu.ops as ops
        _ops_cache = ops
    return _ops_cache


# Read/write hooks for static capture (set by paddle_tpu.jit). Each is either None or
# a callable taking the Tensor.
_read_hook = None
_write_hook = None
# True during BOTH capture phases (probe run and traced replay); lets stateful code
# (e.g. optimizer lr sync) skip out-of-graph writes that would bake constants.
_capture_active = False


def set_capture_hooks(read_hook, write_hook):
    global _read_hook, _write_hook
    prev = (_read_hook, _write_hook)
    _read_hook, _write_hook = read_hook, write_hook
    return prev


def set_capture_active(v: bool) -> bool:
    global _capture_active
    prev = _capture_active
    _capture_active = bool(v)
    return prev


def in_capture() -> bool:
    return _capture_active


def _is_scalar(x) -> bool:
    return isinstance(x, (int, float, bool, complex)) and not isinstance(x, Tensor)


class Tensor:
    __slots__ = ("_data", "stop_gradient", "_grad", "_grad_node", "_out_slot",
                 "_hooks", "_hook_counter", "name", "persistable", "_stamp",
                 "__weakref__", "__dict__")

    def __init__(self, data, dtype=None, place=None, stop_gradient=True,
                 _internal=False):
        global _creation_clock
        if _internal:
            self._data = data
        else:
            if isinstance(data, Tensor):
                arr = data._data
                if dtype is not None:
                    arr = arr.astype(dtype_mod.convert_dtype(dtype))
                self._data = arr
            else:
                self._data = _to_array(data, dtype)
        self.stop_gradient = stop_gradient
        self._grad = None
        self._grad_node = None
        self._out_slot = 0
        self._hooks = {}
        self._hook_counter = 0
        self.name = ""
        self.persistable = False
        _creation_clock += 1
        self._stamp = _creation_clock

    # ----------------------------------------------------------------- data access

    def _read(self):
        if _read_hook is not None:
            _read_hook(self)
        return self._data

    def _write(self, new_array):
        """Rebind the payload (in-place op / optimizer update / set_value).
        The hook fires BEFORE the rebind so capture can snapshot the old value
        (probe runs are rolled back to keep exactly-once step semantics)."""
        if _write_hook is not None:
            _write_hook(self)
        self._data = new_array

    @property
    def data(self):
        return self

    @data.setter
    def data(self, value):
        v = value._data if isinstance(value, Tensor) else _to_array(value, None)
        self._write(v)

    def set_value(self, value):
        v = value._data if isinstance(value, Tensor) else _to_array(value, self.dtype)
        if tuple(v.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {v.shape} vs {self._data.shape}")
        self._write(jnp.asarray(v, self.dtype))

    # ----------------------------------------------------------------- properties

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dim(self):
        return self._data.ndim

    def rank(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def T(self):
        return _ops().t(self)

    @property
    def mT(self):
        return _ops().matrix_transpose(self)

    @property
    def place(self):
        from paddle_tpu.device import _place_of
        return _place_of(self._data)

    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, value):
        if value is not None and not isinstance(value, Tensor):
            value = Tensor(value)
        self._grad = value

    @property
    def is_leaf(self):
        return self._grad_node is None

    def get_tensor(self):
        return self

    # ----------------------------------------------------------------- conversion

    def numpy(self):
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def astype(self, dtype):
        return _ops().cast(self, dtype)

    def cast(self, dtype):
        return _ops().cast(self, dtype)

    def clone(self):
        out = autograd.apply(lambda a: a + 0, self, op_name="clone")
        return out

    def detach(self):
        t = Tensor(self._data, stop_gradient=True, _internal=True)
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def cpu(self):
        return self

    def cuda(self, *a, **k):
        return self

    def to(self, *args, **kwargs):
        # to(dtype) / to(device) / to(device, dtype)
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and (a in dtype_mod._NAME_TO_DTYPE
                                       or a in dtype_mod._ALIASES):
                out = out.astype(a)
            elif isinstance(a, (np.dtype, type)):
                try:
                    out = out.astype(a)
                except TypeError:
                    pass
        return out

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    # ----------------------------------------------------------------- autograd

    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.backward([self], [grad_tensor], retain_graph=retain_graph)

    def register_hook(self, hook):
        self._hook_counter += 1
        hid = self._hook_counter
        self._hooks[hid] = hook

        class RemovableHandle:
            def __init__(h, tensor, hid):
                h._t, h._id = tensor, hid

            def remove(h):
                h._t._hooks.pop(h._id, None)

        return RemovableHandle(self, hid)

    def clear_grad(self, set_to_zero=False):
        if (set_to_zero and self._grad is not None
                and isinstance(self._grad, Tensor)):
            self._grad = Tensor(jnp.zeros_like(self._grad._data), _internal=True)
        else:
            # None, or a SelectedRows grad (no dense buffer to zero)
            self._grad = None

    clear_gradient = clear_grad

    def zero_(self):
        self._write(jnp.zeros_like(self._data))
        return self

    def fill_(self, value):
        self._write(jnp.full_like(self._data, value))
        return self

    # ----------------------------------------------------------------- dunders

    def __repr__(self):
        sg = self.stop_gradient
        try:
            body = repr(np.asarray(self._data))
            body = body[body.find("(") + 1: body.rfind(")")] if body.startswith(
                "array") else body
        except Exception:
            body = f"<traced {self._data}>"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
                f"stop_gradient={sg},\n       {body})")

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        if isinstance(self._data, jax.core.Tracer):
            from paddle_tpu.jit.dy2static import (
                DataDependentControlFlowError, _HINT)
            raise DataDependentControlFlowError(_HINT)
        return bool(self._data)

    def __int__(self):
        return int(self._data)

    def __float__(self):
        return float(self._data)

    def __index__(self):
        if isinstance(self._data, jax.core.Tracer):
            # `range(t)` / `x[t]` on a traced scalar: signal the dy2static
            # retry (the converter lowers for-over-range to a carried while)
            # instead of surfacing jax's ConcretizationTypeError. The raise
            # ALSO inherits TypeError — the index protocol's contract —
            # so numpy/stdlib fallbacks that probe __index__ inside
            # `except TypeError` keep degrading gracefully
            from paddle_tpu.jit.dy2static import (
                DataDependentIndexError, _HINT)
            raise DataDependentIndexError(_HINT)
        return int(self._data)

    def __hash__(self):
        return id(self)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return object.__format__(self, spec)

    # arithmetic — implemented in paddle_tpu.ops and bound here lazily
    def __add__(self, o):
        return _ops().add(self, o)

    def __radd__(self, o):
        return _ops().add(self, o)

    def __sub__(self, o):
        return _ops().subtract(self, o)

    def __rsub__(self, o):
        return _ops().subtract(o, self)

    def __mul__(self, o):
        return _ops().multiply(self, o)

    def __rmul__(self, o):
        return _ops().multiply(self, o)

    def __truediv__(self, o):
        return _ops().divide(self, o)

    def __rtruediv__(self, o):
        return _ops().divide(o, self)

    def __floordiv__(self, o):
        return _ops().floor_divide(self, o)

    def __rfloordiv__(self, o):
        return _ops().floor_divide(o, self)

    def __mod__(self, o):
        return _ops().remainder(self, o)

    def __rmod__(self, o):
        return _ops().remainder(o, self)

    def __pow__(self, o):
        return _ops().pow(self, o)

    def __rpow__(self, o):
        return _ops().pow(o, self)

    def __matmul__(self, o):
        return _ops().matmul(self, o)

    def __rmatmul__(self, o):
        return _ops().matmul(o, self)

    def __neg__(self):
        return _ops().neg(self)

    def __abs__(self):
        return _ops().abs(self)

    def __invert__(self):
        return _ops().logical_not(self)

    def __and__(self, o):
        return _ops().bitwise_and(self, o)

    def __or__(self, o):
        return _ops().bitwise_or(self, o)

    def __xor__(self, o):
        return _ops().bitwise_xor(self, o)

    def __eq__(self, o):
        return _ops().equal(self, o)

    def __ne__(self, o):
        return _ops().not_equal(self, o)

    def __lt__(self, o):
        return _ops().less_than(self, o)

    def __le__(self, o):
        return _ops().less_equal(self, o)

    def __gt__(self, o):
        return _ops().greater_than(self, o)

    def __ge__(self, o):
        return _ops().greater_equal(self, o)

    # ----------------------------------------------------------------- indexing

    def __getitem__(self, idx):
        return _ops().getitem(self, idx)

    def __setitem__(self, idx, value):
        return _ops().setitem(self, idx, value)

    # in-place arithmetic sugar
    def __iadd__(self, o):
        return _ops().add_(self, o)

    def __isub__(self, o):
        return _ops().subtract_(self, o)

    def __imul__(self, o):
        return _ops().multiply_(self, o)

    def __itruediv__(self, o):
        return _ops().divide_(self, o)


def _to_array(data, dtype):
    """Convert arbitrary host data to a jax array with Paddle's dtype defaults
    (python floats / float64 numpy default to the framework default dtype)."""
    want = dtype_mod.convert_dtype(dtype) if dtype is not None else None
    if isinstance(data, jax.Array) or isinstance(data, jax.core.Tracer):
        return data.astype(want) if want is not None and data.dtype != want else data
    if isinstance(data, (bool, int, float, complex)):
        if want is None:
            if isinstance(data, bool):
                want = dtype_mod.bool_
            elif isinstance(data, int):
                want = dtype_mod.int64
            elif isinstance(data, float):
                want = dtype_mod.get_default_dtype()
            else:
                want = dtype_mod.complex64
        return jnp.asarray(data, want)
    explicit_np = isinstance(data, np.ndarray) or np.isscalar(data)
    arr = np.asarray(data)
    if want is None and arr.dtype == np.float64 and not explicit_np:
        # match paddle.to_tensor: python float lists come in as f64 -> default dtype
        want = dtype_mod.get_default_dtype()
    if np.issubdtype(arr.dtype if want is None else np.dtype(want),
                     np.complexfloating) and not _complex_on_device():
        # dev-tunnel backends reject complex transfers; pin to host CPU so the
        # data survives (ops on it then run on the CPU backend)
        return jax.device_put(arr.astype(want) if want is not None else arr,
                              jax.devices("cpu")[0])
    return jnp.asarray(arr, want) if want is not None else jnp.asarray(arr)


_COMPLEX_ON_DEVICE = None


def _complex_on_device() -> bool:
    global _COMPLEX_ON_DEVICE
    if _COMPLEX_ON_DEVICE is None:
        try:
            from jax._src import xla_bridge
            names = set(xla_bridge.backends().keys())
        except Exception:
            names = set()
        _COMPLEX_ON_DEVICE = not (jax.default_backend() == "tpu"
                                  and "axon" in names)
    return _COMPLEX_ON_DEVICE


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """Create a Tensor from python data / numpy / Tensor (ref: ``paddle.to_tensor``,
    `python/paddle/tensor/creation.py`)."""
    if isinstance(data, Tensor):
        arr = data._data
        if dtype is not None:
            arr = arr.astype(dtype_mod.convert_dtype(dtype))
        return Tensor(arr, stop_gradient=stop_gradient, _internal=True)
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


class Parameter(Tensor):
    """A Tensor that is trainable by default (ref: ``paddle.fluid.framework.Parameter``)."""

    def __init__(self, data, dtype=None, stop_gradient=False, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable
                         if trainable is not None else stop_gradient)
        self.persistable = True

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v
