"""``paddle.sysconfig`` (ref: `python/paddle/sysconfig.py` — get_include :20,
get_lib :35): paths for compiling extensions against the framework."""
import os

__all__ = ["get_include", "get_lib"]


def get_include():
    """Directory with the framework's headers (the native shm-queue / any
    cpp_extension sources live under io/native)."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "io",
                        "native")


def get_lib():
    """Directory containing the framework's built native libraries."""
    return get_include()
