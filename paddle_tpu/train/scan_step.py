"""Scan-over-layers donated GPT train step.

ONE jitted program per (shape, microbatch count) holding the entire
training hot path:

- forward/backward as `jax.lax.scan` over the STACKED [nl, ...] block
  leaves (models/gpt.py `scan_loss`) — compile wall is O(1) in depth
  instead of O(nl), which is what lets the 8-device CPU dryrun finish;
- gradient-accumulation microbatching: a scan over microbatches
  accumulates grads in f32 and the optimizer applies ONCE;
- ZeRO-1 (arxiv 2004.13336): optimizer moments (and fp32 masters) are
  laid out and constrained sharded over the `dp` mesh axis, so each
  replica materializes 1/dp of the optimizer state and computes only its
  shard of the weight update; GSPMD re-gathers the updated params;
- buffer donation (`donate_argnums=(0, 1)`): params + optimizer state
  update in place, no step-to-step copy of the model.

The paddle `Optimizer` object stays the checkpoint truth: the step seeds
its state FROM the optimizer's accumulators and `sync_to_model()` writes
params/moments back before any state_dict/eval consumer reads them.
"""
from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.mesh import get_mesh
from paddle_tpu.observability import metrics
from paddle_tpu.observability.flight_recorder import (Watchdog,
                                                      default_deadline,
                                                      flight)
from paddle_tpu.testing import faults


# per-chip peak for MFU denominators — bench.py imports THIS constant so
# its rung MFU and the `train.mfu` gauge can never disagree on the peak
V5E_BF16_PEAK = 197e12


def safe_backend() -> str:
    """`jax.default_backend()` that cannot raise ("cpu" when the platform
    plugin is wedged): telemetry reads must never take a hot path down
    (the BENCH_r05 lesson). The one such probe in the repo — bench.py's
    `_platform()` delegates here."""
    try:
        return jax.default_backend()
    except Exception:  # noqa: BLE001 — plugin init errors of any type
        return "cpu"


def peak_flops() -> float:
    """Per-chip MFU denominator: v5e bf16 peak on TPU, a nominal
    1 TFLOP/s elsewhere. The ONE peak predicate in the repo — bench.py
    imports this, so its rung MFU and `train.mfu` cannot disagree."""
    return V5E_BF16_PEAK if safe_backend() == "tpu" else 1e12


class ScanUnsupported(ValueError):
    """(model, optimizer, config) cannot take the scanned fused train-step
    path; callers fall back to the unrolled per-layer capture."""


def _leaf_keys(tree):
    for grp in ("blocks", "top"):
        for k in tree[grp]:
            yield grp, k


def _layer_param_name(grp, key):
    return f"gpt.h.0.{key}" if grp == "blocks" else key


class ScanTrainStep:
    """Captured donated train step for a GPTForCausalLM.

    model       : GPTForCausalLM (attention_dropout must be 0 to train)
    optimizer   : a _FUSABLE paddle optimizer (SGD/Momentum/Adam/AdamW/
                  Adagrad/RMSProp/Adadelta/Adamax) whose grad_clip is None
                  or ClipGradByGlobalNorm
    microbatches: default split of each step's batch (scan + f32 grad
                  accumulation, single optimizer apply)
    zero1       : True / False / "auto" (on when the mesh's dp axis > 1)
    grad_reducer: optional ``(loss, grads) -> (loss, grads)`` host hook
                  for CROSS-PROCESS data parallelism (multi-host fleets
                  whose jaxlib cannot compile one program over all
                  processes — `train/elastic.py` FleetReducer averages
                  through the coordination-service KV). When set the step
                  SPLITS into two programs: a grads program (loss +
                  pre-clip f32 grads out), the reducer on the host, then
                  a donated apply program (finite-check + clip + fused
                  update over the REDUCED values, so every rank skips or
                  applies identically). None (the default) keeps the
                  single fused program — bit-identical to before.
    """

    def __init__(self, model, optimizer, *, microbatches=1, zero1="auto",
                 mesh=None, axis="dp", use_loss_mask=False, seed=0,
                 grad_reducer=None):
        from paddle_tpu.models.gpt import GPTForCausalLM
        from paddle_tpu.nn.clip import ClipGradByGlobalNorm
        if not isinstance(model, GPTForCausalLM):
            raise ScanUnsupported(
                f"scan train step needs GPTForCausalLM, got "
                f"{type(model).__name__}")
        cfg = model.cfg
        if cfg.attention_dropout:
            raise ScanUnsupported(
                "attention_dropout > 0 has no scan-path implementation")
        names_update = getattr(optimizer, "functional_update", None)
        if names_update is None or not getattr(optimizer, "_FUSABLE", False):
            raise ScanUnsupported(
                f"{type(optimizer).__name__} has no pure fused update")
        if getattr(optimizer, "_l1_decay", 0.0):
            raise ScanUnsupported("L1 decay is not scan-fusable")
        clip = optimizer._grad_clip
        if clip is not None and not isinstance(clip, ClipGradByGlobalNorm):
            raise ScanUnsupported(
                f"{type(clip).__name__} is not scan-fusable (only "
                "ClipGradByGlobalNorm)")
        self._clip_norm = float(clip.clip_norm) if clip is not None else None
        self.model, self.opt, self.cfg = model, optimizer, cfg
        self.microbatches = max(1, int(microbatches))
        self.mesh = mesh if mesh is not None else get_mesh()
        self._axis = axis
        dp = self.mesh.shape.get(axis, 1) if self.mesh is not None else 1
        self.zero1 = bool(dp > 1) if zero1 == "auto" else bool(zero1)
        self.use_loss_mask = bool(use_loss_mask)
        self._state_names, self._update = optimizer.functional_update()
        self._key = jax.random.PRNGKey(seed)
        self._dirty = False
        self._compiles = 0
        self._seen_sigs = set()
        # bad-step containment (docs/ROBUSTNESS.md "Training fault
        # tolerance"): the program reduces an all-finite flag over loss +
        # grads and SKIPS the optimizer apply when it trips — same program,
        # zero recompiles. The host-side ladder lives in CheckpointManager.
        self.bad_steps = 0
        self.consecutive_bad_steps = 0
        self.last_step_ok = True
        self._grad_reducer = grad_reducer
        self.refresh_from_model()
        if self.mesh is not None:
            # pin the output placements to the input placements: params and
            # opt state come back exactly where they went in, so the SECOND
            # step sees identical (aval, sharding) signatures and the
            # program compiles exactly once on the mesh
            repl = NamedSharding(self.mesh, PartitionSpec())
            out_sh = (repl, repl, self._param_sh, self._state_sh)
        else:
            out_sh = None
        if grad_reducer is None:
            self._jit = jax.jit(self._make_step_fn(), donate_argnums=(0, 1),
                                **({"out_shardings": out_sh}
                                   if out_sh is not None else {}))
            self._jit_grads = self._jit_apply = None
        else:
            # split pipeline: grads out (params NOT donated — the apply
            # still reads them), host reduce, donated apply. Two programs,
            # each compiling exactly once (test_no_retrace pin).
            self._jit = None
            self._jit_grads = jax.jit(self._make_grads_fn())
            self._jit_apply = jax.jit(
                self._make_apply_fn(), donate_argnums=(0, 1),
                **({"out_shardings": out_sh} if out_sh is not None else {}))

    # ------------------------------------------------------------- state io

    def refresh_from_model(self):
        """(Re)pull params from the model and optimizer state from the
        optimizer's accumulators (zeros where absent), applying ZeRO-1
        placements to the state leaves. Called at init and after any
        out-of-band eager update (hapi ragged batch, set_state_dict)."""
        from paddle_tpu.models.gpt import stack_gpt_params
        from paddle_tpu.distributed.sharding import zero1_partition_spec
        state = self.model.state_dict()
        self._param_objs = dict(state)
        nl = self.cfg.num_layers
        self._params = stack_gpt_params(
            {k: t._data for k, t in state.items()}, mesh=self.mesh)
        opt, meta, opt_state = self.opt, {}, {"blocks": {}, "top": {}}
        param_sh = {"blocks": {}, "top": {}}
        state_sh = {"blocks": {}, "top": {}}
        replicated = NamedSharding(self.mesh, PartitionSpec()) \
            if self.mesh is not None else None
        use_master = bool(getattr(opt, "_use_master_weights", False))
        for grp, key in _leaf_keys(self._params):
            leaf = self._params[grp][key]
            pobjs = ([state[f"gpt.h.{i}.{key}"] for i in range(nl)]
                     if grp == "blocks" else [state[key]])
            lws = {opt._lr_wd_of(p, 1.0) for p in pobjs}
            if len(lws) != 1:
                raise ScanUnsupported(
                    f"per-layer lr/weight-decay differ across the stacked "
                    f"leaf {key!r}: {sorted(lws)} — the scanned step "
                    "updates all layers of a leaf with one (lr, wd)")
            lr_mult, wd = lws.pop()
            sh = getattr(leaf, "sharding", None)
            base_spec = tuple(sh.spec) if isinstance(sh, NamedSharding) \
                else None
            zspec = zero1_partition_spec(
                leaf.shape, self.mesh, self._axis,
                base_spec=base_spec) if self.zero1 else None
            zsh = NamedSharding(self.mesh, zspec) if zspec is not None \
                else None
            master = use_master and leaf.dtype != jnp.float32
            psh = sh if isinstance(sh, NamedSharding) else replicated
            if psh is not None and not isinstance(sh, NamedSharding):
                # commit unplaced params to the mesh (replicated) so the
                # step-1 and step-2 input signatures match (compile once)
                leaf = jax.device_put(leaf, psh)
                self._params[grp][key] = leaf
            ssh = zsh if zsh is not None else (replicated or None)
            meta[(grp, key)] = {
                "lr_mult": float(lr_mult), "wd": float(wd),
                "zsh": zsh,
                "psh": psh,
                "master": master,
                "need_clip": all(getattr(p, "need_clip", True)
                                 for p in pobjs),
            }
            param_sh[grp][key] = psh
            st = {}
            for name in self._state_names:
                arrs = [opt.get_state_array(name, p) for p in pobjs]
                if all(a is None for a in arrs):
                    stacked = opt._functional_state_init(name, leaf.shape)
                else:
                    stacked = jnp.stack([
                        jnp.asarray(a, jnp.float32) if a is not None
                        else opt._functional_state_init(name, leaf.shape[1:])
                        for a in arrs])
                    if grp == "top":
                        stacked = stacked[0]
                st[name] = jax.device_put(stacked, ssh) if ssh is not None \
                    else stacked
            if master:
                srcs = []
                for p in pobjs:
                    m = opt._master_weights.get(id(p))
                    m = m._data if m is not None else getattr(
                        p, "_master", None)
                    m = m._data if isinstance(m, Tensor) else m
                    srcs.append(jnp.asarray(m if m is not None else p._data,
                                            jnp.float32))
                mast = jnp.stack(srcs) if grp == "blocks" else srcs[0]
                st["master"] = jax.device_put(mast, ssh) if ssh is not None \
                    else mast
            opt_state[grp][key] = st
            state_sh[grp][key] = {n: ssh for n in st}
        self._meta = meta
        self._opt_state = opt_state
        self._param_sh = param_sh
        self._state_sh = state_sh
        self._dirty = False
        metrics.gauge("train.opt_state_bytes").set(self.opt_state_bytes())
        metrics.gauge("train.zero1").set(1.0 if self.zero1 else 0.0)

    def sync_to_model(self):
        """Write the step's params back into the model's Parameters and its
        optimizer state back into the accumulators/master weights, so
        state_dict / eval / the decode paths see the trained values."""
        from paddle_tpu.models.gpt import unstack_gpt_params
        arrs = unstack_gpt_params(self._params)
        nl = self.cfg.num_layers
        for name, t in self._param_objs.items():
            t._write(arrs[name])
        for grp, key in _leaf_keys(self._params):
            st = self._opt_state[grp][key]
            pobjs = ([self._param_objs[f"gpt.h.{i}.{key}"]
                      for i in range(nl)] if grp == "blocks"
                     else [self._param_objs[key]])
            for name in self._state_names:
                for i, p in enumerate(pobjs):
                    self.opt.set_state_array(
                        name, p, st[name][i] if grp == "blocks"
                        else st[name])
            if "master" in st:
                for i, p in enumerate(pobjs):
                    self.opt.set_master_array(
                        p, st["master"][i] if grp == "blocks"
                        else st["master"])
        self._dirty = False

    @property
    def dirty(self):
        return self._dirty

    @property
    def compile_count(self):
        return self._compiles

    def opt_state_bytes(self):
        """Per-replica optimizer-state footprint: each leaf counted at its
        SHARD size, so ZeRO-1 shows the ~1/dp saving the sharding buys."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(self._opt_state):
            sh = getattr(leaf, "sharding", None)
            shape = sh.shard_shape(leaf.shape) if hasattr(sh, "shard_shape") \
                else leaf.shape
            total += int(np.prod(shape) or 1) * leaf.dtype.itemsize
        return total

    # ------------------------------------------------------------- the step

    def _make_grads_fn(self):
        """(params, xs, ys, ms, key_data, poison) -> (loss, f32 grads) —
        the forward/backward half: scan over layers, microbatch
        accumulation, NO optimizer math. Standalone program in reducer
        mode; inlined by `_make_step_fn` for the fused single-program
        path (identical op sequence either way)."""
        from paddle_tpu.models.gpt import scan_loss
        cfg, mesh = self.cfg, self.mesh
        use_mask = self.use_loss_mask

        def loss_fn(params, x, y, m, key):
            if mesh is not None and "dp" in mesh.axis_names \
                    and x.shape[0] % mesh.shape["dp"] == 0:
                sh = NamedSharding(mesh, PartitionSpec("dp", None))
                x = jax.lax.with_sharding_constraint(x, sh)
                y = jax.lax.with_sharding_constraint(y, sh)
            return scan_loss(params, x, y, cfg, loss_mask=m, training=True,
                             dropout_key=key)

        def grads_of(params, xs, ys, ms, keys):
            def one(x, y, m, k):
                return jax.value_and_grad(loss_fn)(params, x, y, m, k)

            if xs.shape[0] == 1:
                loss, g = one(xs[0], ys[0],
                              ms[0] if ms is not None else None, keys[0])
                return loss, jax.tree_util.tree_map(
                    lambda a: a.astype(jnp.float32), g)

            def micro(carry, inp):
                gacc, lacc = carry
                if ms is None:
                    x, y, k = inp
                    m = None
                else:
                    x, y, m, k = inp
                l, g = one(x, y, m, k)
                gacc = jax.tree_util.tree_map(
                    lambda a, b: a + b.astype(jnp.float32), gacc, g)
                return (gacc, lacc + l), None

            zeros = jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, jnp.float32), params)
            xs_in = (xs, ys, keys) if ms is None else (xs, ys, ms, keys)
            (gsum, lsum), _ = jax.lax.scan(
                micro, (zeros, jnp.zeros((), jnp.float32)), xs_in)
            inv = 1.0 / xs.shape[0]
            return lsum * inv, jax.tree_util.tree_map(
                lambda a: a * inv, gsum)

        def grads_fn(params, xs, ys, ms, key_data, poison):
            key = jax.random.wrap_key_data(key_data)
            mkeys = jax.random.split(key, xs.shape[0])
            loss, grads = grads_of(params, xs, ys, ms if use_mask else None,
                                   mkeys)
            # poison: 0.0 normally, NaN when the train.step_nan fault is
            # armed — rides the loss into the finite reduce so chaos tests
            # drive the skip path through the SAME compiled program(s). In
            # reducer mode the poisoned loss travels THROUGH the reduce,
            # so one rank's injected NaN skips the step on every rank.
            return loss + poison, grads

        return grads_fn

    def _make_apply_fn(self):
        """(params, opt_state, loss, grads, lr, t) -> (loss, ok,
        new_params, new_state) — the optimizer half: all-finite reduce,
        global-norm clip, fused update, in-program bad-step skip."""
        names, update = self._state_names, self._update
        meta, clip_norm = self._meta, self._clip_norm

        def apply_fn(params, opt_state, loss, grads, lr, t):
            # all-finite reduce over loss + raw (pre-clip) grads: one
            # non-finite value anywhere makes ok False and the apply below
            # becomes the identity — the step is SKIPPED in-program, no
            # host round-trip, no recompile (test_no_retrace.py pin)
            ok = jnp.isfinite(loss)
            for gk in _leaf_keys(grads):
                ok = ok & jnp.all(jnp.isfinite(grads[gk[0]][gk[1]]))
            if clip_norm is not None:
                sq = jnp.zeros((), jnp.float32)
                for gk in _leaf_keys(grads):
                    if meta[gk]["need_clip"]:
                        sq = sq + jnp.sum(grads[gk[0]][gk[1]] ** 2)
                gn = jnp.sqrt(sq)
                scale = clip_norm / jnp.maximum(gn, clip_norm)
                grads = jax.tree_util.tree_map(lambda a: a * scale, grads)
            new_params = {"blocks": {}, "top": {}}
            new_state = {"blocks": {}, "top": {}}
            for grp, k in _leaf_keys(params):
                p, g = params[grp][k], grads[grp][k]
                st, mt = opt_state[grp][k], meta[(grp, k)]
                st0 = st               # pre-update state: the skip target
                if mt["zsh"] is not None:
                    # ZeRO-1: grads + moments dp-sharded, so the update math
                    # partitions over dp and each replica touches only its
                    # shard; the downcast param below is constrained back to
                    # the param's own placement and GSPMD all-gathers it
                    g = jax.lax.with_sharding_constraint(g, mt["zsh"])
                    st = {n: jax.lax.with_sharding_constraint(v, mt["zsh"])
                          for n, v in st.items()}
                p32 = st["master"] if mt["master"] else (
                    p.astype(jnp.float32) if p.dtype != jnp.float32 else p)
                new_p32, new_sts = update(
                    p32, g, [st[n] for n in names],
                    lr * mt["lr_mult"], jnp.asarray(mt["wd"], jnp.float32),
                    t)
                out = dict(zip(names, new_sts))
                if mt["master"]:
                    out["master"] = new_p32
                if mt["zsh"] is not None:
                    out = {n: jax.lax.with_sharding_constraint(v, mt["zsh"])
                           for n, v in out.items()}
                new_p = new_p32.astype(p.dtype)
                # non-finite step: keep the OLD params/state (NaNs computed
                # on the not-taken side are discarded by the select)
                new_p = jnp.where(ok, new_p, p)
                out = {n: jnp.where(ok, v, st0[n]) for n, v in out.items()}
                if mt["psh"] is not None:
                    new_p = jax.lax.with_sharding_constraint(new_p, mt["psh"])
                new_params[grp][k] = new_p
                new_state[grp][k] = out
            return loss, ok, new_params, new_state

        return apply_fn

    def _make_step_fn(self):
        """The fused single-program path: grads half composed with apply
        half inside ONE donated program — the exact op sequence the
        pre-split implementation traced, so losses stay bit-identical."""
        grads_fn = self._make_grads_fn()
        apply_fn = self._make_apply_fn()

        def step_fn(params, opt_state, xs, ys, ms, lr, t, key_data, poison):
            loss, grads = grads_fn(params, xs, ys, ms, key_data, poison)
            return apply_fn(params, opt_state, loss, grads, lr, t)

        return step_fn

    def step(self, x, y, loss_mask=None, microbatches=None):
        """One fused train step. x: [B, S] int ids, y: [B, S] labels
        (paddle Tensors or arrays); B must divide by the microbatch count.
        Returns the mean f32 loss as a python float."""
        # int32 ids/labels + an x64-disabled trace: the program must not mix
        # s64 loop indices into the SPMD-partitioned scan backward (XLA's
        # partitioner rejects s64/s32 compares on the dus indices), and the
        # vocab never exceeds int32 anyway. Same convention as the decode
        # paths (flash kernel x64_off).
        xd = x._data if hasattr(x, "_data") else jnp.asarray(x)
        yd = y._data if hasattr(y, "_data") else jnp.asarray(y)
        xd = xd.astype(jnp.int32) if xd.dtype != jnp.int32 else xd
        yd = yd.astype(jnp.int32) if yd.dtype != jnp.int32 else yd
        m = self.microbatches if microbatches is None else int(microbatches)
        b = xd.shape[0]
        if b % m:
            raise ValueError(f"batch {b} not divisible by microbatches {m}")
        xs = xd.reshape(m, b // m, *xd.shape[1:])
        ys = yd.reshape(m, b // m, *yd.shape[1:])
        if self.use_loss_mask:
            if loss_mask is None:
                raise ValueError("step captured with use_loss_mask=True "
                                 "needs a loss_mask")
            md = loss_mask._data if hasattr(loss_mask, "_data") \
                else jnp.asarray(loss_mask)
            ms = md.reshape(m, b // m, *md.shape[1:])
        else:
            ms = jnp.zeros((m, 1), jnp.float32)    # placeholder, DCE'd
        lr = jnp.asarray(self.opt.get_lr(), jnp.float32)
        t = jnp.asarray(self.opt._global_step + 1, jnp.float32)
        self._key, sub = jax.random.split(self._key)
        # train.step_nan chaos site: poison is a PROGRAM INPUT (0.0 or NaN),
        # so an injected bad step exercises the warm program, not a retrace
        injected = faults.ENABLED and faults.fire("train.step_nan")
        poison = jnp.asarray(float("nan") if injected else 0.0, jnp.float32)
        before = self._cache_size()
        # dispatch marker BEFORE the jit call: if the step (or its compile)
        # wedges, the watchdog dump's last ring event shows WHERE — a
        # post-hoc record would vanish with the hang
        flight.record("train.dispatch", step=self.opt._global_step + 1,
                      shape=str(tuple(xs.shape)))
        t0 = time.perf_counter()
        from jax.experimental import disable_x64
        with disable_x64():
            if self._grad_reducer is None:
                loss, ok, self._params, self._opt_state = self._jit(
                    self._params, self._opt_state, xs, ys, ms, lr, t,
                    jax.random.key_data(sub), poison)
            else:
                # split pipeline (cross-process dp): local grads program,
                # host-side reduce over the fleet (the reducer raises
                # typed PeerLost when a peer dies mid-step), donated
                # apply over the REDUCED loss+grads — ok/skip decisions
                # are computed from identical values on every rank
                g_loss, grads = self._jit_grads(
                    self._params, xs, ys, ms,
                    jax.random.key_data(sub), poison)
                g_loss, grads = self._grad_reducer(g_loss, grads)
                grads = jax.tree_util.tree_map(
                    lambda a: jnp.asarray(a, jnp.float32), grads)
                loss, ok, self._params, self._opt_state = self._jit_apply(
                    self._params, self._opt_state,
                    jnp.asarray(g_loss, jnp.float32), grads, lr, t)
        lossf = float(loss)                        # sync: real device time
        okb = bool(ok)
        dt = time.perf_counter() - t0
        after = self._cache_size()
        if before >= 0 and after >= 0:
            compiled = after > before
        else:
            # jax internals moved (_cache_size gone): fall back to tracking
            # input signatures ourselves — one compile per distinct shape
            sig = (xs.shape, ys.shape, str(xs.dtype))
            compiled = sig not in self._seen_sigs
            self._seen_sigs.add(sig)
        tokens = int(np.prod(xd.shape))
        from paddle_tpu.models.gpt import analytic_flops_per_token
        flops = analytic_flops_per_token(self.cfg, int(xd.shape[-1])) * tokens
        # flops covers the whole global batch, so the peak must cover the
        # whole mesh — a per-chip denominator would read ~device_count too
        # high and clamp at 1.0 exactly on multichip deployments
        n_dev = self.mesh.size if self.mesh is not None else 1
        mfu = min(1.0, flops / (max(dt, 1e-9) * peak_flops() * n_dev))
        if compiled:
            self._compiles += 1
            metrics.counter("train.compile_count").inc()
            metrics.gauge("train.compile_ms").set(dt * 1e3)
            metrics.add_span("train.compile", t0, dt, cat="compile")
        elif okb:
            metrics.gauge("train.step_ms").set(dt * 1e3)
            metrics.histogram("train.step_seconds").observe(dt)
            # goodput + model FLOPs utilization from the ANALYTIC flop
            # count (models/gpt.py, 6N + attention term) — STEADY steps
            # only, like step_ms: a compile step's dt would read as a
            # collapsed mfu and fake the exact alarm the gauge exists to
            # raise (mfu down while step_ms holds = the batch shrank)
            metrics.gauge("train.mfu").set(mfu)
            metrics.gauge("train.goodput_tokens_per_s").set(
                tokens / max(dt, 1e-9))
        metrics.counter("train.steps").inc()
        metrics.counter("train.microbatches").inc(m)
        self.last_step_ok = okb
        if not okb:
            # non-finite loss/grads: the program kept the old params/state,
            # so the step NEVER HAPPENED as far as the optimizer clock, the
            # lr schedule, and the token/goodput accounting are concerned.
            # The host only counts it and flight-records — the rollback
            # ladder (M consecutive) is CheckpointManager.after_step's job.
            self.bad_steps += 1
            self.consecutive_bad_steps += 1
            metrics.counter("train.bad_steps").inc()
            flight.record("train.bad_step", step=self.opt._global_step + 1,
                          loss=lossf, consecutive=self.consecutive_bad_steps,
                          injected=bool(injected))
            return lossf
        self.consecutive_bad_steps = 0
        metrics.counter("train.tokens").inc(tokens)
        flight.record("train.step", step=self.opt._global_step + 1,
                      loss=lossf, ms=round(dt * 1e3, 3),
                      mfu=round(mfu, 5), compiled=bool(compiled))
        self.opt._global_step += 1
        self.opt._sync_lr_tensor(self.opt.get_lr())
        self._dirty = True
        return lossf

    def _cache_size(self):
        try:
            if self._jit is not None:
                return self._jit._cache_size()
            # split (reducer) mode: compile accounting covers BOTH programs
            return (self._jit_grads._cache_size()
                    + self._jit_apply._cache_size())
        except Exception:  # noqa: BLE001 — jax internals moved
            return -1

    def start_watchdog(self, deadline_s=None, dump_dir=None,
                       interval_s=None):
        """Arm a stall watchdog over the train loop: if `step()` stops
        completing (a wedged device call, a hung collective) for
        ``deadline_s`` (default ``PADDLE_WATCHDOG_S``; <= 0 disables and
        returns None), the flight-recorder ring + metrics snapshot dump to
        a JSON file. The driver owns the lifecycle: call before the loop,
        `.stop()` after — an armed watchdog treats the loop as always-busy,
        so don't leave it running across eval/checkpoint pauses longer
        than the deadline."""
        deadline = default_deadline() if deadline_s is None \
            else float(deadline_s)
        if deadline <= 0:
            return None
        return Watchdog("train",
                        progress=lambda: self.opt._global_step,
                        deadline_s=deadline, dump_dir=dump_dir,
                        interval_s=interval_s).start()

    __call__ = step
